"""Flash attention: a first-party Pallas TPU kernel for the attention hot op.

Forward is a Pallas kernel (``_fwd_kernel``): the grid is
``(batch*heads, q_blocks, k_blocks)`` with the k dimension innermost, so the
online-softmax state (running max ``m``, normalizer ``l``, accumulator ``acc``)
lives in VMEM scratch and carries across k steps — the [T, T] score matrix
never exists, each program touches one ``[blk_q, D] × [blk_k, D]`` tile pair on
the MXU. The kernel also emits the log-sum-exp per query row, which makes the
backward pass a pure recompute: ``custom_vjp`` re-forms each score block from
(Q, K, LSE). On TPU the backward is two Pallas kernels (dk/dv walking q
blocks, dq walking k blocks, both with the causal block skip); elsewhere a
blockwise ``lax.scan`` computes the same math — memory stays O(T·blk) in both
directions.

Dispatch: on TPU (and block-aligned shapes) the Pallas kernel runs; elsewhere a
fused jnp path computes the same math (tests compare both, and run the kernel
in interpret mode). The TPU build adds this op beyond reference parity — the
reference has no attention anywhere (SURVEY.md §2.4). It is the single-device
attention of :class:`raydp_tpu.models.transformer.TransformerLM`; the
sequence-sharded path uses :mod:`raydp_tpu.ops.ring_attention` instead.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# swept on TPU v5e at T=8192, H=8, D=64 (benchmarks/flash_block_sweep.py,
# 2026-07-30): fwd 9.1ms @128x128 -> 1.23ms @1024x1024 (55.9 TFLOP/s);
# fwd+bwd with the Pallas backward kernels 2.41ms @512x1024 vs 2.44ms
# @1024x1024 (~100 TFLOP/s, within 1.5%) — the fwd winner decides.
# 2048-wide blocks gain nothing (and 2048x2048 fails VMEM).
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, blk_q: int, blk_k: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _body():
        q = q_ref[0]                            # [blk_q, D], native dtype
        k = k_ref[0]                            # [blk_k, D]
        v = v_ref[0]                            # [blk_k, D]

        # native-dtype MXU matmul (bf16 x bf16 -> f32); upcasting inputs to
        # f32 first would cost ~4x MXU throughput for no accuracy gain over
        # the f32 accumulator
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [blk_q, blk_k]

        if causal:
            s = _mask_causal(s, qi, ki, blk_q, blk_k)

        m_prev = m_scr[:, 0]                                # [blk_q]
        l_prev = l_scr[:, 0]
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new[:, None])
        correction = jnp.exp(m_prev - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=-1)
        acc_scr[:] = (acc_scr[:] * correction[:, None]
                      + jax.lax.dot_general(
                          p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32))
        m_scr[:, 0] = m_new
        l_scr[:, 0] = l_new

    if causal:
        # causal block skipping: a k block strictly above the triangle (its
        # first key after this q block's last query) contributes exactly
        # zero — skip both matmuls, halving causal FLOPs
        pl.when(qi * blk_q + (blk_q - 1) >= ki * blk_k)(_body)
    else:
        _body()

    @pl.when(ki == num_k - 1)
    def _finalize():
        l_fin = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0] = (acc_scr[:] / l_fin[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:, 0] + jnp.log(l_fin)


def _fwd_pallas(q3, k3, v3, *, scale: float, causal: bool, blk_q: int,
                blk_k: int, interpret: bool):
    """q3/k3/v3: [BH, T, D] → (out [BH, T, D], lse [BH, T])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q3.shape
    grid = (bh, t // blk_q, t // blk_k)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, qi, ki: (b, qi, 0)),
            # [BH, 1, T]: trailing block dims (1, blk_q) satisfy TPU tiling
            pl.BlockSpec((1, 1, blk_q), lambda b, qi, ki: (b, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, 128), jnp.float32),   # m (lane-padded)
            pltpu.VMEM((blk_q, 128), jnp.float32),   # l
            pltpu.VMEM((blk_q, d), jnp.float32),     # acc
        ],
        # bh and q blocks are independent; only the k walk carries state
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q3, k3, v3)
    return out, lse.reshape(bh, t)


# ---------------------------------------------------------------------------
# Fused jnp path (non-TPU fallback; also the forward for lse on that path)
# ---------------------------------------------------------------------------
def _fwd_jnp(q3, k3, v3, *, scale: float, causal: bool):
    s = jnp.einsum("bqd,bkd->bqk", q3.astype(jnp.float32),
                   k3.astype(jnp.float32)) * scale
    if causal:
        t = q3.shape[1]
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(mask[None], s, _NEG_INF)
    lse = jax.nn.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bqk,bkd->bqd", p, v3.astype(jnp.float32))
    return out.astype(q3.dtype), lse


# ---------------------------------------------------------------------------
# Pallas backward kernels: recompute p from (q, k, lse), causal block skip.
# Split in the standard way — one kernel accumulates dk/dv walking q blocks,
# one accumulates dq walking k blocks — so each output block is written once
# and all accumulation stays in VMEM scratch.
# ---------------------------------------------------------------------------
def _mask_causal(s, qi, ki, blk_q: int, blk_k: int):
    """Apply the causal mask to a score block (shared by fwd + both bwds)."""
    q_pos = qi * blk_q + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
    k_pos = ki * blk_k + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _recompute_p_ds(q, k, v, do, lse, delta, qi, ki,
                    *, scale: float, causal: bool, blk_q: int, blk_k: int):
    """Re-form a score block from (q, k, lse) and compute (p, ds) — the flash
    backward identity ds = p ⊙ (do·vᵀ − delta)·scale, shared by the dk/dv and
    dq kernels so forward and backward masking cannot desynchronize."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale       # [blk_q, blk_k]
    if causal:
        s = _mask_causal(s, qi, ki, blk_q, blk_k)
    p = jnp.exp(s - lse[:, None])                         # true softmax rows
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None]) * scale
    return p, ds


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_scr, dv_scr,
                     *, scale: float, causal: bool, blk_q: int, blk_k: int):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)
    num_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _body():
        q, do = q_ref[0], do_ref[0]            # [blk_q, D]
        p, ds = _recompute_p_ds(
            q, k_ref[0], v_ref[0], do, lse_ref[0, 0], delta_ref[0, 0],
            qi, ki, scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(qi * blk_q + (blk_q - 1) >= ki * blk_k)(_body)
    else:
        _body()

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr,
                   *, scale: float, causal: bool, blk_q: int, blk_k: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _body():
        k = k_ref[0]
        _, ds = _recompute_p_ds(
            q_ref[0], k, v_ref[0], do_ref[0], lse_ref[0, 0], delta_ref[0, 0],
            qi, ki, scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(qi * blk_q + (blk_q - 1) >= ki * blk_k)(_body)
    else:
        _body()

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_pallas(res, g, *, scale: float, causal: bool, blk_q: int,
                blk_k: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q3, k3, v3, out, lse = res
    bh, t, d = q3.shape
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, 1, t)
    lse3 = lse.reshape(bh, 1, t)
    num_q, num_k = t // blk_q, t // blk_k

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k),
        grid=(bh, num_k, num_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, ki, qi: (b, qi, 0)),  # q
            pl.BlockSpec((1, blk_k, d), lambda b, ki, qi: (b, ki, 0)),  # k
            pl.BlockSpec((1, blk_k, d), lambda b, ki, qi: (b, ki, 0)),  # v
            pl.BlockSpec((1, blk_q, d), lambda b, ki, qi: (b, qi, 0)),  # do
            pl.BlockSpec((1, 1, blk_q), lambda b, ki, qi: (b, 0, qi)),  # lse
            pl.BlockSpec((1, 1, blk_q), lambda b, ki, qi: (b, 0, qi)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, blk_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, d), jnp.float32),
            pltpu.VMEM((blk_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q3, k3, v3, do, lse3, delta)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k),
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, qi, ki: (b, qi, 0)),  # q
            pl.BlockSpec((1, blk_k, d), lambda b, qi, ki: (b, ki, 0)),  # k
            pl.BlockSpec((1, blk_k, d), lambda b, qi, ki: (b, ki, 0)),  # v
            pl.BlockSpec((1, blk_q, d), lambda b, qi, ki: (b, qi, 0)),  # do
            pl.BlockSpec((1, 1, blk_q), lambda b, qi, ki: (b, 0, qi)),  # lse
            pl.BlockSpec((1, 1, blk_q), lambda b, qi, ki: (b, 0, qi)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), q3.dtype)],
        scratch_shapes=[pltpu.VMEM((blk_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q3, k3, v3, do, lse3, delta)[0]
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Blockwise backward (flash recompute from LSE), shared by both paths
# ---------------------------------------------------------------------------
def _bwd_blockwise(res, g, *, scale: float, causal: bool, blk_k: int):
    q3, k3, v3, out, lse = res
    bh, t, d = q3.shape
    blk = _fit_block(t, blk_k)
    num_k = t // blk

    qf = q3.astype(jnp.float32)
    do = g.astype(jnp.float32)
    delta = jnp.sum(do * out.astype(jnp.float32), axis=-1)   # [BH, Tq]
    q_pos = jnp.arange(t)

    def step(dq, j):
        k_blk = lax.dynamic_slice_in_dim(k3, j * blk, blk, 1).astype(jnp.float32)
        v_blk = lax.dynamic_slice_in_dim(v3, j * blk, blk, 1).astype(jnp.float32)
        s = jnp.einsum("bqd,bkd->bqk", qf, k_blk) * scale
        if causal:
            k_pos = j * blk + jnp.arange(blk)
            s = jnp.where((q_pos[:, None] >= k_pos[None, :])[None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])                      # [BH, Tq, blk]
        dv_blk = jnp.einsum("bqk,bqd->bkd", p, do)
        dp = jnp.einsum("bqd,bkd->bqk", do, v_blk)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, k_blk)
        dk_blk = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq, (dk_blk, dv_blk)

    dq, (dk_blocks, dv_blocks) = lax.scan(
        step, jnp.zeros_like(qf), jnp.arange(num_k))
    dk = dk_blocks.transpose(1, 0, 2, 3).reshape(bh, t, d)
    dv = dv_blocks.transpose(1, 0, 2, 3).reshape(bh, t, d)
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


# ---------------------------------------------------------------------------
# Public op with custom VJP, [B, T, H, D] layout
# ---------------------------------------------------------------------------
def _fit_block(t: int, blk: int) -> int:
    """Shrink blk by halving until it divides t (down to 1), so the grid and
    the blockwise backward always cover the full sequence."""
    blk = min(blk, t)
    while t % blk:
        blk //= 2
    return max(blk, 1)


def _use_pallas(t: int, d: int, blk_q: int, blk_k: int,
                interpret: bool) -> bool:
    aligned = t % blk_q == 0 and t % blk_k == 0
    if interpret:
        return aligned
    if jax.default_backend() != "tpu":
        return False
    # block dims equal to the full array dim satisfy TPU tiling, so d needs no
    # 128 alignment; q/k blocks must be sublane-aligned themselves —
    # ``_fit_block`` caps blocks at t, which is not necessarily a multiple of
    # 8 (e.g. t=20 → blk=20), so check it here rather than assume
    return (aligned and d % 8 == 0
            and blk_q >= 8 and blk_k >= 8
            and blk_q % 8 == 0 and blk_k % 8 == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q3, k3, v3, scale, causal, blk_q, blk_k, interpret):
    out, _ = _flash_fwd(q3, k3, v3, scale, causal, blk_q, blk_k, interpret)
    return out


def _flash_fwd(q3, k3, v3, scale, causal, blk_q, blk_k, interpret):
    t, d = q3.shape[1], q3.shape[2]
    if _use_pallas(t, d, blk_q, blk_k, interpret):
        out, lse = _fwd_pallas(q3, k3, v3, scale=scale, causal=causal,
                               blk_q=blk_q, blk_k=blk_k, interpret=interpret)
    else:
        out, lse = _fwd_jnp(q3, k3, v3, scale=scale, causal=causal)
    return out, (q3, k3, v3, out, lse)


def _flash_bwd(scale, causal, blk_q, blk_k, interpret, res, g):
    t, d = res[0].shape[1], res[0].shape[2]
    if _use_pallas(t, d, blk_q, blk_k, interpret):
        return _bwd_pallas(res, g, scale=scale, causal=causal,
                           blk_q=blk_q, blk_k=blk_k, interpret=interpret)
    return _bwd_blockwise(res, g, scale=scale, causal=causal, blk_k=blk_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False):
    """Memory-efficient exact attention. q/k/v: [B, T, H, D] → [B, T, H, D]."""
    b, t, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    blk_q = _fit_block(t, block_q)
    blk_k = _fit_block(t, block_k)

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    out3 = _flash(to3(q), to3(k), to3(v), scale, causal, blk_q, blk_k,
                  interpret)
    return out3.reshape(b, h, t, d).transpose(0, 2, 1, 3)
