"""Ring attention: exact attention over sequence-sharded inputs.

The sequence axis is sharded over the mesh's ``seq`` axis; each device holds a
[B, T/n, H, D] slice of Q/K/V. K/V blocks rotate around the ring with
``ppermute`` while every device accumulates its queries' attention over each
passing block using the online-softmax (flash) recurrence, so the full [T, T]
score matrix never materializes and memory stays O(T/n). Collectives ride ICI
neighbor links — the layout the hardware gives ring ``ppermute`` for free.

The reference framework has no sequence parallelism at all (SURVEY.md §2.4: "every
other strategy is absent") — this op is the long-context capability the TPU build
adds. Two properties keep it viable at pod scale:

- **bounded local memory**: within a ring step the passing K/V block is folded
  in ``chunk_size`` key chunks (inner ``lax.scan``), so the largest live score
  block is [B, H, T/n, chunk] — without it a 128k-token sequence over 16
  devices would materialize 8k x 8k scores per head per step;
- **causal step skipping**: a block arriving from a strictly-future source
  contributes nothing under causality; ``lax.cond`` skips its entire update
  (the ``ppermute`` still runs — the ring must keep rotating), saving ~half
  the FLOPs the way the flash kernel skips whole blocks above the triangle.

The single-device memory-efficient kernel lives separately in
:mod:`raydp_tpu.ops.flash_attention`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _local_attention_update(q, k, v, m, l, acc, mask=None, scale=1.0):
    """One online-softmax update of (m, l, acc) with a new K/V block.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]; m, l: [B, H, Tq]; acc: [B, Tq, H, D].
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B, H, Tq, Tk]
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    m_blk = jnp.max(scores, axis=-1)                      # [B, H, Tq]
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows: exp(-inf - -inf) -> exp(0) would be wrong
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    correction = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    acc_new = acc * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def _folded_block_update(q, k_blk, v_blk, m, l, acc, q_positions, k_pos0,
                         scale: float, causal: bool, chunk: Optional[int]):
    """Fold one K/V block into (m, l, acc), ``chunk`` keys at a time. The
    key dim is zero-padded up to a chunk multiple and the pad keys masked
    out, so the memory bound holds for EVERY t_local (a prime t_local does
    not degenerate into single-key chunks)."""
    b, tk, h, d = k_blk.shape

    if chunk is None or chunk >= tk:
        if causal:
            k_positions = k_pos0 + jnp.arange(tk)
            mask = (q_positions[:, None] >= k_positions[None, :])[None, None]
        else:
            mask = None
        return _local_attention_update(q, k_blk.astype(jnp.float32),
                                       v_blk.astype(jnp.float32),
                                       m, l, acc, mask=mask, scale=scale)

    n = -(-tk // chunk)                    # ceil: ragged tail padded + masked
    pad = n * chunk - tk
    if pad:
        k_blk = jnp.pad(k_blk, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v_blk = jnp.pad(v_blk, ((0, 0), (0, pad), (0, 0), (0, 0)))
    kc = k_blk.reshape(b, n, chunk, h, d).transpose(1, 0, 2, 3, 4)
    vc = v_blk.reshape(b, n, chunk, h, d).transpose(1, 0, 2, 3, 4)

    def inner(carry, xs):
        m, l, acc = carry
        k_c, v_c, i = xs
        offsets = i * chunk + jnp.arange(chunk)
        valid = (offsets < tk)[None, :]                       # mask pad keys
        if causal:
            k_positions = k_pos0 + offsets
            valid = valid & (q_positions[:, None] >= k_positions[None, :])
        m, l, acc = _local_attention_update(
            q, k_c.astype(jnp.float32), v_c.astype(jnp.float32),
            m, l, acc, mask=valid[None, None], scale=scale)
        return (m, l, acc), None

    (m, l, acc), _ = lax.scan(inner, (m, l, acc), (kc, vc, jnp.arange(n)))
    return m, l, acc


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = True,
                   scale: Optional[float] = None,
                   chunk_size: Optional[int] = 2048):
    """Exact attention for sequence-sharded q/k/v; call inside ``shard_map``.

    Shapes per device: q, k, v = [B, T_local, H, D]. Returns [B, T_local, H, D].
    ``chunk_size`` caps the live score block at [B, H, T_local, chunk_size]
    (None = fold each arriving block in one piece).
    """
    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    q_positions = my_index * t_local + jnp.arange(t_local)  # global q positions

    from raydp_tpu.parallel.mesh import vary_manual
    try:
        vma = tuple(jax.typeof(q).vma) or (axis_name,)
    except Exception:
        vma = (axis_name,)
    m0 = vary_manual(jnp.full((b, h, t_local), -jnp.inf, jnp.float32), vma)
    l0 = vary_manual(jnp.zeros((b, h, t_local), jnp.float32), vma)
    acc0 = vary_manual(jnp.zeros((b, t_local, h, d), jnp.float32), vma)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    qf = q.astype(jnp.float32)

    def step(carry, step_idx):
        m, l, acc, k_blk, v_blk = carry
        # the block currently on this device originated at (my_index - step)
        src = (my_index - step_idx) % axis_size
        k_pos0 = src * t_local

        def update(args):
            m, l, acc = args
            return _folded_block_update(qf, k_blk, v_blk, m, l, acc,
                                        q_positions, k_pos0, scale, causal,
                                        chunk_size)

        if causal:
            # a block from a strictly-future source is fully masked: skip the
            # whole update (the rotation below still runs)
            m, l, acc = lax.cond(src <= my_index, update,
                                 lambda args: args, (m, l, acc))
        else:
            m, l, acc = update((m, l, acc))
        # rotate K/V to the next neighbor (overlaps with next local compute
        # when XLA schedules the collective-permute asynchronously)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (m, l, acc, k_next, v_next), None

    (m, l, acc, _, _), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(axis_size))
    l = jnp.maximum(l, 1e-20)
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, causal: bool = True,
                           seq_axis: str = "seq", batch_axes=("data", "fsdp"),
                           head_axis: str = "tensor",
                           chunk_size: Optional[int] = 2048):
    """shard_map wrapper: [B, T, H, D] arrays sharded (batch over data axes,
    sequence over ``seq_axis``, heads over ``head_axis`` when present) → same
    sharding out. Ring + head sharding compose: each (seq, tensor) tile ships
    only its own heads' K/V around the ring."""
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    batch = tuple(a for a in batch_axes if a in mesh.axis_names
                  and mesh.shape[a] > 1)
    bspec = batch if len(batch) > 1 else (batch[0] if batch else None)
    hspec = head_axis if (head_axis in mesh.axis_names
                          and mesh.shape[head_axis] > 1) else None
    spec = P(bspec, seq_axis, hspec, None)

    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal,
                           chunk_size=chunk_size)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def dense_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None):
    """Unsharded reference implementation (for tests and single-device use)."""
    b, t, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
