"""raydp_tpu.parallel — meshes, shardings, and collectives.

The reference's parallelism inventory is DP-only, realized through five different
collective stacks (SURVEY.md §2.4-2.5: torch DDP, oneCCL, TF MWMS, Horovod,
XGBoost Rabit). The TPU-native design collapses all of them into one mechanism:
a ``jax.sharding.Mesh`` over the pod plus in-graph XLA collectives inserted by
``jit`` from sharding annotations — gradients ride ICI ``psum``, not NCCL rings.
The mesh here is multi-axis from day one (``stage``/``data``/``fsdp``/
``tensor``/``seq``/``expert``) so PP/TP/FSDP/sequence/expert sharding are
additive strategies, not rewrites (SURVEY.md §2.4 closing note).
"""

from raydp_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    batch_sharding,
    replicated,
    param_sharding_rules,
    shard_params,
)
from raydp_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from raydp_tpu.parallel.roles import (
    addressable_nbytes,
    classify_param,
    describe_roles,
    role_partition_spec,
)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "batch_sharding",
    "replicated",
    "param_sharding_rules",
    "shard_params",
    "pipeline_apply",
    "stack_stage_params",
    "classify_param",
    "role_partition_spec",
    "describe_roles",
    "addressable_nbytes",
]
