"""Mesh construction and sharding helpers.

Axis convention (sizes multiply to the device count):

- ``stage``   — pipeline parallel: layer stages, activations ppermute forward
  (see :mod:`raydp_tpu.parallel.pipeline`).
- ``data``    — data parallel: batch dim sharded, params replicated, grad psum.
- ``fsdp``    — params+optimizer sharded over this axis, all-gathered per layer.
- ``tensor``  — tensor parallel (Megatron-style column/row splits).
- ``seq``     — sequence/context parallel (ring attention / all-to-all).
- ``expert``  — expert parallel (MoE experts and DLRM embedding shards).

On hardware, axis order maps inner axes to ICI neighbors — keep ``tensor``/
``seq`` innermost so their heavy collectives ride the fastest links, and
``stage`` outermost (its per-microbatch boundary hops are the rarest, and on
multi-slice deployments they are what crosses DCN). The scaling-book recipe:
pick a mesh, annotate shardings, let XLA insert collectives.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

AXES = ("stage", "data", "fsdp", "expert", "seq", "tensor")


@dataclass
class MeshSpec:
    """Sizes per axis; ``data=-1`` absorbs all remaining devices."""

    data: int = -1
    fsdp: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1
    stage: int = 1

    def sizes(self, num_devices: int) -> Dict[str, int]:
        fixed = {"fsdp": self.fsdp, "expert": self.expert, "seq": self.seq,
                 "tensor": self.tensor, "stage": self.stage}
        known = int(np.prod(list(fixed.values())))
        data = self.data
        if data == -1:
            if num_devices % known != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by "
                    f"stage*fsdp*expert*seq*tensor={known}")
            data = num_devices // known
        total = data * known
        if total != num_devices:
            raise ValueError(
                f"mesh {dict(data=data, **fixed)} needs {total} devices, "
                f"have {num_devices}")
        return {"data": data, **fixed}


def make_mesh(spec: Optional[Union[MeshSpec, Dict[str, int]]] = None,
              devices=None, axis_names: Sequence[str] = AXES):
    """Build a ``jax.sharding.Mesh`` over all (or given) devices.

    ``spec`` may be a :class:`MeshSpec` or a plain axis-size dict
    (``dict(fsdp=4, tensor=2)``) — the estimator's ``mesh_spec=`` argument
    accepts either, so callers need not import MeshSpec to go sharded."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    if isinstance(spec, dict):
        unknown = set(spec) - set(AXES)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}; "
                             f"have {AXES}")
        spec = MeshSpec(**spec)
    spec = spec or MeshSpec()
    sizes = spec.sizes(len(devices))
    shape = tuple(sizes[a] for a in axis_names)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def vary_manual(x, axes: Sequence[str]):
    """Mark ``x`` varying over the manual mesh ``axes`` it is not already
    varying over — the newer-jax shard_map vma compat shim (carry inits made
    with ``zeros_like`` are invariant and must be cast before mixing with
    varying values; ``pcast`` rejects axes already in the input's vma).
    No-op on older jax. Shared by ring attention and the pipeline."""
    import jax
    from jax import lax

    if not axes or not (hasattr(lax, "pcast") or hasattr(lax, "pvary")):
        return x
    try:
        cur = set(jax.typeof(x).vma)
    except Exception:
        cur = set()
    need = tuple(a for a in axes if a not in cur)
    if not need:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, need, to="varying")
    return lax.pvary(x, need)


def data_axes(mesh) -> Tuple[str, ...]:
    """Axes the batch dimension is sharded over: data + fsdp (fsdp shards the
    batch too — params gather per layer, grads reduce-scatter)."""
    return tuple(a for a in ("data", "fsdp") if a in mesh.axis_names
                 and mesh.shape[a] > 1) or ("data",)


def batch_sharding(mesh, extra_batch_axes: Sequence[str] = (),
                   seq: bool = False):
    """Sharding of a batch-leading array: dim 0 over the data axes (plus any
    ``extra_batch_axes`` folded into the same dim). With ``seq=True`` and a
    >1 ``seq`` extent, dim 1 — the sequence dim — additionally shards over
    ``seq``, so long-context activations never materialize whole per device
    (callers must only apply the seq form to ndim >= 2 arrays)."""
    from jax.sharding import NamedSharding, PartitionSpec
    axes = tuple(data_axes(mesh)) + tuple(extra_batch_axes)
    entry = axes if len(axes) > 1 else axes[0]
    if seq and seq_extent(mesh) > 1:
        return NamedSharding(mesh, PartitionSpec(entry, "seq"))
    return NamedSharding(mesh, PartitionSpec(entry))


def seq_extent(mesh) -> int:
    """Size of the mesh's ``seq`` axis (1 when absent) — the gate every
    seq-sharding call site checks before extending specs past dim 0."""
    return int(mesh.shape.get("seq", 1)) if "seq" in mesh.axis_names else 1


def stage_extent(mesh) -> int:
    """Size of the mesh's ``stage`` axis (1 when absent) — the gate the
    estimator checks before routing training through the GPipe schedule.
    ``stage`` stays the OUTERMOST mesh axis (:data:`AXES`): its per-tick
    boundary hops are the rarest collective, so they ride the slowest links
    (cross-slice DCN on multi-slice deployments)."""
    return int(mesh.shape.get("stage", 1)) if "stage" in mesh.axis_names else 1


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())


def param_sharding_rules(mesh, rules: Optional[List[Tuple[str, Tuple]]] = None):
    """Compile path-pattern → PartitionSpec rules into a tree-mapping function.

    ``rules`` is an ordered list of ``(substring, spec_tuple)``; the first
    matching substring of the parameter path wins. Leaves no rule matches go
    to the role policy (:mod:`raydp_tpu.parallel.roles` — embeddings over
    fsdp×tensor, kernels over fsdp/tensor by dimension, biases replicated;
    opt out with ``RDT_TRAIN_SHARD_ROLES=0``), whose fallback-of-last-resort
    matches the legacy behavior: replicated (pure DP, the reference's only
    strategy), or fsdp sharding on the largest divisible dim when an ``fsdp``
    axis is present.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from raydp_tpu import knobs
    from raydp_tpu.parallel.roles import role_partition_spec

    fsdp = mesh.shape.get("fsdp", 1) > 1
    use_roles = bool(knobs.get("RDT_TRAIN_SHARD_ROLES"))

    def spec_for(path: str, leaf) -> NamedSharding:
        if rules:
            for pat, spec in rules:
                if pat in path:
                    return NamedSharding(mesh, PartitionSpec(*spec))
        if use_roles:
            return NamedSharding(mesh, role_partition_spec(
                mesh, path, tuple(getattr(leaf, "shape", ()))))
        if fsdp and hasattr(leaf, "ndim") and leaf.ndim >= 1:
            dims = getattr(leaf, "shape", ())
            if dims:
                # shard the largest dim divisible by the fsdp axis
                order = sorted(range(len(dims)), key=lambda i: -dims[i])
                for i in order:
                    if dims[i] % mesh.shape["fsdp"] == 0 and dims[i] > 1:
                        spec = [None] * len(dims)
                        spec[i] = "fsdp"
                        return NamedSharding(mesh, PartitionSpec(*spec))
        return NamedSharding(mesh, PartitionSpec())

    def shardings_of(tree):
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        out = []
        for path, leaf in flat:
            path_str = "/".join(
                str(getattr(p, "key", getattr(p, "name", p))) for p in path)
            out.append(spec_for(path_str, leaf))
        return jax.tree_util.tree_unflatten(treedef, out)

    return shardings_of


def shard_params(params, mesh, rules=None):
    """Place a parameter tree according to the rules (device_put per leaf)."""
    import jax
    shardings = param_sharding_rules(mesh, rules)(params)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), params, shardings)
