"""Pipeline parallelism: collective GPipe over the mesh's ``stage`` axis.

The reference has no pipeline (or any non-data) parallelism (SURVEY.md §2.4);
this is part of the TPU build's complete strategy matrix (dp/fsdp/tp/sp/ep/pp).

TPU-idiomatic design — no per-stage processes, no send/recv runtime: ONE
compiled SPMD program under ``shard_map``. Per-stage parameters are stacked on
a leading axis and sharded over ``stage``; microbatches march through the
classic GPipe schedule inside a ``lax.scan``, activations hopping stage →
stage+1 with ``lax.ppermute`` each tick (on hardware these hops ride
neighboring ICI/DCN links — ``stage`` is the outermost mesh axis). The
backward pass needs no hand scheduling: AD of scan+ppermute IS the reverse
pipeline (ppermute transposes to the reverse permutation), so one
``jax.grad`` over :func:`pipeline_apply` trains the whole pipeline.

Total ticks = n_micro + n_stages - 1; the (n_stages - 1)-tick bubble is the
standard GPipe cost, amortized by more microbatches.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def stack_stage_params(param_trees) -> Any:
    """Stack per-stage parameter pytrees on a new leading 'stage' axis
    (stage-homogeneous layers: identical structure and shapes required)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *param_trees)


def _pipeline_local(stage_params, x_micro, *, fn, stage_axis: str,
                    n_micro: int):
    """Per-stage body under shard_map. ``stage_params`` leaves arrive with
    leading axis ``layers_per_stage`` (this stage's contiguous slice of the
    layer stack); ``x_micro`` is [n_micro, ...] (batch dim possibly
    data-sharded)."""
    n_stages = lax.psum(1, stage_axis)
    s = lax.axis_index(stage_axis)
    # this stage's shard holds its CONTIGUOUS run of layers (leading dim =
    # layers_per_stage); apply them in order — one stage may own several
    layers_per_stage = jax.tree.leaves(stage_params)[0].shape[0]

    def apply_stage(x):
        for i in range(layers_per_stage):
            x = fn(jax.tree.map(lambda p: p[i], stage_params), x)
        return x

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    # carry inits must vary over the union of the manual axes of everything
    # they mix with — the inputs' axes plus stage (state mixes with
    # params-derived activations from tick 1 on)
    from raydp_tpu.parallel.mesh import vary_manual
    try:
        in_vma = tuple(jax.typeof(x_micro).vma)
    except Exception:
        in_vma = ()
    vma = tuple(dict.fromkeys(in_vma + (stage_axis,)))
    state0 = vary_manual(jnp.zeros_like(x_micro[0]), vma)
    out0 = vary_manual(jnp.zeros_like(x_micro), vma)

    def tick(carry, t):
        state, outputs = carry
        # stage 0 injects microbatch t while t < n_micro; other stages
        # consume the activation that arrived from stage-1 on the last hop
        inject = x_micro[jnp.clip(t, 0, n_micro - 1)]
        cur = jnp.where(s == 0, inject, state)
        y = apply_stage(cur)
        # the last stage finished microbatch (t - (n_stages - 1))
        idx = t - (n_stages - 1)
        live = (s == n_stages - 1) & (idx >= 0)
        outputs = jnp.where(
            live, outputs.at[jnp.clip(idx, 0, n_micro - 1)].set(y), outputs)
        state = lax.ppermute(y, stage_axis, perm)
        return (state, outputs), None

    (_, outputs), _ = lax.scan(tick, (state0, out0),
                               jnp.arange(n_micro + n_stages - 1))
    # outputs live on the last stage only; replicate them across the axis
    # (masked psum — every other stage holds zeros)
    return lax.psum(jnp.where(s == n_stages - 1, outputs, 0.0), stage_axis)


def pipeline_apply(fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stage_params: Any, x_micro: jnp.ndarray, mesh,
                   stage_axis: str = "stage"):
    """Run ``x_micro`` ([n_micro, mb, ...]) through ``n_stages`` pipeline
    stages; ``fn(params, x) -> y`` is one stage (y must have x's shape/dtype —
    stage-homogeneous pipelines, the transformer-block case).

    ``stage_params`` leaves are stacked [n_layers, ...]
    (:func:`stack_stage_params`; ``n_layers`` must be a multiple of
    ``n_stages`` — each stage applies its contiguous run of layers in order)
    and sharded over ``stage_axis``; returns
    [n_micro, mb, ...] outputs, replicated over the stage axis. The
    microbatch dim (axis 1) is sharded over the mesh's data axes inside the
    pipeline, so pp×dp does dp-partitioned work per stage rather than
    redundant replication; tp composes inside a stage as usual.
    Differentiable end-to-end: ``jax.grad`` of a loss over ``pipeline_apply``
    backpropagates through the scan + ppermute schedule (the reverse
    pipeline), with stage-sharded gradients landing on their stage.
    """
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    from raydp_tpu.parallel.mesh import data_axes

    n_stages = mesh.shape[stage_axis]
    n_micro = int(x_micro.shape[0])
    n_layers = stage_params_leading_dim(stage_params)
    if n_stages > 1 and n_layers % n_stages != 0:
        raise ValueError(
            f"{n_layers} stacked layers cannot split over {n_stages} pipeline "
            f"stages (must divide evenly; each stage applies its contiguous "
            f"run of layers in order)")
    if n_stages <= 1:
        # no stage axis: plain sequential application of every stage
        def seq_apply(x):
            for i in range(stage_params_leading_dim(stage_params)):
                x = fn(jax.tree.map(lambda p: p[i], stage_params), x)
            return x
        return jax.vmap(seq_apply)(x_micro)

    daxes = tuple(a for a in data_axes(mesh) if mesh.shape[a] > 1)
    dp = 1
    for a in daxes:
        dp *= mesh.shape[a]
    mb = int(x_micro.shape[1])
    pad = 0
    if daxes and mb % dp != 0:
        # microbatch not divisible by the data extent: pad zero rows up to
        # the next divisible count and slice them back off the outputs —
        # the pipeline stays dp-sharded instead of silently replicating
        # every microbatch (the pre-r17 fallback). Padded rows are zeros;
        # callers mask their loss rows the same way the feed's pad-and-mask
        # tail does, and the outputs sliced off here never reach a loss.
        pad = dp - mb % dp
        widths = [(0, 0)] * x_micro.ndim
        widths[1] = (0, pad)
        x_micro = jnp.pad(x_micro, widths)
        from raydp_tpu import metrics
        metrics.inc("train_padded_rows_total", pad * n_micro)
    if daxes:
        mspec = P(None, daxes if len(daxes) > 1 else daxes[0])
    else:  # single-device data extent: nothing to shard the rows over
        mspec = P()
    pspec = jax.tree.map(lambda _: P(stage_axis), stage_params)
    body = functools.partial(_pipeline_local, fn=fn, stage_axis=stage_axis,
                             n_micro=n_micro)
    out = shard_map(body, mesh=mesh,
                    in_specs=(pspec, mspec), out_specs=mspec)(
                        stage_params, x_micro)
    return out[:, :mb] if pad else out


def stage_params_leading_dim(stage_params) -> int:
    return int(jax.tree.leaves(stage_params)[0].shape[0])
