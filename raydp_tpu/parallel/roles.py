"""Role-driven parameter sharding: pytree path → PartitionSpec.

The mesh (:mod:`raydp_tpu.parallel.mesh`) has carried ``fsdp``/``tensor``
axes since the seed, but choosing a PartitionSpec per parameter was left to
hand-written ``param_rules``. This module is the SpecLayout-style policy that
closes the gap: classify every parameter (and optimizer-state leaf) by its
*role* — read off the pytree path and the leaf's shape — and emit the spec
that role wants on this mesh:

- **embedding tables** (path names an embedding, 2-D): rows sharded over
  ``fsdp`` × ``tensor`` — the vocab dim is the big dim and gathers are
  per-lookup, so both axes pay off together;
- **projection / dense kernels** (≥ 2-D): Megatron-style ``tensor`` on the
  output (last) dim, ``fsdp`` on the largest remaining dim — FSDP all-gathers
  params per layer so its dim choice is a memory layout, not a math change;
- **biases / norm scales / scalars** (≤ 1-D): replicated — sharding a few
  hundred bytes buys nothing and costs a gather.

A dim is only ever sharded when the axis has size > 1 **and** divides it;
anything unshardable degrades axis by axis down to replicated, so the policy
is total (never raises on an odd shape). Optimizer state inherits its
parameter's spec for free: optax moment trees (adam ``mu``/``nu``) mirror the
parameter paths and shapes, so the same classification fires — the FSDP
memory win covers the Adam moments, not just the weights.

``param_sharding_rules`` consults this policy (behind ``RDT_TRAIN_SHARD_ROLES``)
whenever no explicit rule matches, so ``mesh_spec=dict(fsdp=..., tensor=...)``
alone yields a fully sharded train state.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

#: path substrings that mark an embedding table (lowercased match). "embed"
#: catches flax ``nn.Embed`` scopes and the conventional ``embedding`` /
#: ``embed_tokens`` / ``token_embedder`` spellings in one token.
EMBEDDING_TOKENS = ("embed",)

#: path substrings that mark a stage-stacked leaf — per-layer parameter
#: pytrees stacked on a leading axis by
#: :func:`raydp_tpu.parallel.pipeline.stack_stage_params`. The leading dim is
#: the layer stack and shards over the mesh's ``stage`` axis; the REST of the
#: shape classifies through the ordinary role policy (the token is stripped
#: before inner classification so a stacked kernel still gets tensor/fsdp on
#: its inner dims).
STAGE_TOKENS = ("stage_stack",)

REPLICATED = "replicated"
EMBEDDING = "embedding"
KERNEL = "kernel"


def classify_param(path: str, shape: Tuple[int, ...]) -> str:
    """The role of one leaf: ``embedding`` | ``kernel`` | ``replicated``.

    Works on parameter paths AND their optimizer-state mirrors (e.g.
    ``opt_state/0/mu/Dense_0/kernel`` classifies like the kernel itself);
    scalars (step counts) and 1-D leaves (biases, norm scales) replicate.
    """
    ndim = len(shape)
    if ndim <= 1:
        return REPLICATED
    low = path.lower()
    if ndim == 2 and any(tok in low for tok in EMBEDDING_TOKENS):
        return EMBEDDING
    return KERNEL


def _divides(dim: int, size: int) -> bool:
    return size > 1 and dim > 1 and dim % size == 0


def role_partition_spec(mesh, path: str, shape: Tuple[int, ...]):
    """The PartitionSpec the leaf's role wants on ``mesh`` (total: degrades
    to replicated whenever an axis is absent, size 1, or does not divide).

    Stage-stacked leaves (path contains a :data:`STAGE_TOKENS` token) put the
    mesh's ``stage`` axis on their leading (layer-stack) dim when it divides,
    then classify the INNER shape through the ordinary role policy — a
    stacked kernel is still a kernel on dims 1..n. Optimizer-state mirrors
    (adam ``mu``/``nu``) inherit this for free: their paths carry the same
    token."""
    from jax.sharding import PartitionSpec

    low = path.lower()
    if any(tok in low for tok in STAGE_TOKENS) and len(shape) >= 1:
        stage = int(mesh.shape.get("stage", 1))
        lead = shape[0]
        head = "stage" if _divides(lead, stage) else None
        inner_path = low
        for tok in STAGE_TOKENS:
            inner_path = inner_path.replace(tok, "")
        inner = role_partition_spec(mesh, inner_path, tuple(shape[1:]))
        return PartitionSpec(head, *inner)

    fsdp = int(mesh.shape.get("fsdp", 1))
    tensor = int(mesh.shape.get("tensor", 1))
    role = classify_param(path, shape)
    if role == REPLICATED or (fsdp <= 1 and tensor <= 1):
        return PartitionSpec()

    spec: list = [None] * len(shape)
    if role == EMBEDDING:
        # rows (vocab) over the fsdp×tensor product when it divides; else
        # whichever single axis does; embedding dim stays replicated
        rows = shape[0]
        if _divides(rows, fsdp * tensor) and fsdp > 1 and tensor > 1:
            spec[0] = ("fsdp", "tensor")
        elif _divides(rows, fsdp):
            spec[0] = "fsdp"
        elif _divides(rows, tensor):
            spec[0] = "tensor"
        return PartitionSpec(*spec)

    # kernels: tensor on the output (last) dim, fsdp on the largest
    # remaining divisible dim (deterministic tie-break: lower index wins)
    if _divides(shape[-1], tensor):
        spec[-1] = "tensor"
    if fsdp > 1:
        order = sorted(range(len(shape)), key=lambda i: (-shape[i], i))
        for i in order:
            if spec[i] is None and _divides(shape[i], fsdp):
                spec[i] = "fsdp"
                break
    return PartitionSpec(*spec)


#: the remat policy vocabulary (RDT_TRAIN_REMAT / FlaxEstimator remat=)
REMAT_MODES = ("none", "dots", "full")


def remat_policy(mode: str):
    """The ``jax.checkpoint`` saveable policy for one remat mode — the
    activation-side mirror of the parameter role policy above. Roles split
    the forward's residuals the same way they split the weights:

    - ``dots`` keeps the MXU-bound products — the outputs of kernel and
      embedding contractions (:data:`KERNEL`/:data:`EMBEDDING` leaves are
      exactly the operands of those dots) — and recomputes the cheap
      elementwise glue (:data:`REPLICATED`-role bias adds, activations,
      norms) in the backward;
    - ``full`` saves nothing: every residual recomputes, trading the most
      FLOPs for the smallest live-activation footprint;
    - ``none`` returns None — the caller skips ``jax.checkpoint`` entirely
      and XLA keeps all residuals (the fastest, fattest default).
    """
    import jax

    if mode == "none":
        return None
    if mode == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    if mode == "full":
        return jax.checkpoint_policies.nothing_saveable
    raise ValueError(
        f"unknown remat mode {mode!r}: expected one of {REMAT_MODES}")


def apply_remat(fn, mode: str):
    """``fn`` wrapped in ``jax.checkpoint`` under ``mode``'s policy
    (``none`` returns ``fn`` untouched). Applied to the train-step forward
    so the whole per-microbatch activation set obeys the policy."""
    import jax

    policy = remat_policy(mode)
    if policy is None:
        return fn
    return jax.checkpoint(fn, policy=policy)


#: the roles a remat policy may key on: the param-role vocabulary plus
#: ``default`` (the fallback mode — a bare mode string is sugar for
#: ``default=<mode>``, which keeps the pre-r20 global knob meaning).
REMAT_ROLES = (REPLICATED, EMBEDDING, KERNEL, "default")


def parse_remat_policy(spec: str) -> Dict[str, str]:
    """``RDT_TRAIN_REMAT`` / ``remat=`` grammar → a total role→mode map.

    Accepts either a bare mode (``"dots"`` — the pre-r20 global form, now
    meaning *default policy for every role*) or a comma-separated
    ``role=mode`` list (``"embedding=none,kernel=dots,default=full"``).
    Roles come from :data:`REMAT_ROLES`, modes from :data:`REMAT_MODES`;
    anything else raises ``ValueError`` — validated eagerly, long before any
    compile. The returned dict always carries a ``default`` entry
    (``none`` unless the spec set one)."""
    spec = (spec or "none").strip()
    policy: Dict[str, str] = {}
    if "=" not in spec:
        if spec not in REMAT_MODES:
            raise ValueError(
                f"unknown remat mode {spec!r}: expected one of {REMAT_MODES} "
                f"or a 'role=mode,...' policy over roles {REMAT_ROLES}")
        policy["default"] = spec
        return policy
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad remat policy entry {part!r} in {spec!r}: expected "
                f"role=mode")
        role, _, mode = (p.strip() for p in part.partition("="))
        if role not in REMAT_ROLES:
            raise ValueError(
                f"unknown remat role {role!r} in {spec!r}: expected one of "
                f"{REMAT_ROLES}")
        if mode not in REMAT_MODES:
            raise ValueError(
                f"unknown remat mode {mode!r} for role {role!r} in {spec!r}: "
                f"expected one of {REMAT_MODES}")
        if role in policy:
            raise ValueError(f"duplicate remat role {role!r} in {spec!r}")
        policy[role] = mode
    policy.setdefault("default", "none")
    return policy


def remat_mode_for_role(policy: Dict[str, str], role: str) -> str:
    """The mode a parsed policy assigns to one param role (``default``
    fallback — the policy map is total by construction)."""
    return policy.get(role, policy["default"])


def segment_role(tree) -> str:
    """The dominant param role of a (sub)tree, weighted by leaf bytes — the
    role whose parameters own most of the segment's memory decides which
    remat mode the segment's forward runs under, exactly how the param specs
    are chosen leaf-by-leaf. Empty trees classify ``replicated``."""
    import jax

    weights: Dict[str, int] = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        path_str = "/".join(
            str(getattr(p, "key", getattr(p, "name", p))) for p in path)
        shape = tuple(getattr(leaf, "shape", ()))
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            size = 1
            for d in shape:
                size *= int(d)
            nbytes = size * 4
        role = classify_param(path_str, shape)
        weights[role] = weights.get(role, 0) + int(nbytes)
    if not weights:
        return REPLICATED
    return max(weights.items(), key=lambda kv: (kv[1], kv[0]))[0]


def describe_roles(tree) -> dict:
    """Debug/bench helper: path → (role, shape) for every leaf of ``tree``."""
    import jax

    out = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        path_str = "/".join(
            str(getattr(p, "key", getattr(p, "name", p))) for p in path)
        shape = tuple(getattr(leaf, "shape", ()))
        out[path_str] = (classify_param(path_str, shape), shape)
    return out


def addressable_nbytes(tree) -> int:
    """Bytes of ``tree`` actually resident on THIS process's devices —
    replicated leaves count one copy per addressable device (that IS the
    memory they occupy), sharded leaves only their local shards. The number
    the fsdp-vs-replicated HBM headroom claim is measured in."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is not None:
            total += sum(s.data.nbytes for s in shards)
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total
