"""Role-driven parameter sharding: pytree path → PartitionSpec.

The mesh (:mod:`raydp_tpu.parallel.mesh`) has carried ``fsdp``/``tensor``
axes since the seed, but choosing a PartitionSpec per parameter was left to
hand-written ``param_rules``. This module is the SpecLayout-style policy that
closes the gap: classify every parameter (and optimizer-state leaf) by its
*role* — read off the pytree path and the leaf's shape — and emit the spec
that role wants on this mesh:

- **embedding tables** (path names an embedding, 2-D): rows sharded over
  ``fsdp`` × ``tensor`` — the vocab dim is the big dim and gathers are
  per-lookup, so both axes pay off together;
- **projection / dense kernels** (≥ 2-D): Megatron-style ``tensor`` on the
  output (last) dim, ``fsdp`` on the largest remaining dim — FSDP all-gathers
  params per layer so its dim choice is a memory layout, not a math change;
- **biases / norm scales / scalars** (≤ 1-D): replicated — sharding a few
  hundred bytes buys nothing and costs a gather.

A dim is only ever sharded when the axis has size > 1 **and** divides it;
anything unshardable degrades axis by axis down to replicated, so the policy
is total (never raises on an odd shape). Optimizer state inherits its
parameter's spec for free: optax moment trees (adam ``mu``/``nu``) mirror the
parameter paths and shapes, so the same classification fires — the FSDP
memory win covers the Adam moments, not just the weights.

``param_sharding_rules`` consults this policy (behind ``RDT_TRAIN_SHARD_ROLES``)
whenever no explicit rule matches, so ``mesh_spec=dict(fsdp=..., tensor=...)``
alone yields a fully sharded train state.
"""

from __future__ import annotations

from typing import Optional, Tuple

#: path substrings that mark an embedding table (lowercased match). "embed"
#: catches flax ``nn.Embed`` scopes and the conventional ``embedding`` /
#: ``embed_tokens`` / ``token_embedder`` spellings in one token.
EMBEDDING_TOKENS = ("embed",)

REPLICATED = "replicated"
EMBEDDING = "embedding"
KERNEL = "kernel"


def classify_param(path: str, shape: Tuple[int, ...]) -> str:
    """The role of one leaf: ``embedding`` | ``kernel`` | ``replicated``.

    Works on parameter paths AND their optimizer-state mirrors (e.g.
    ``opt_state/0/mu/Dense_0/kernel`` classifies like the kernel itself);
    scalars (step counts) and 1-D leaves (biases, norm scales) replicate.
    """
    ndim = len(shape)
    if ndim <= 1:
        return REPLICATED
    low = path.lower()
    if ndim == 2 and any(tok in low for tok in EMBEDDING_TOKENS):
        return EMBEDDING
    return KERNEL


def _divides(dim: int, size: int) -> bool:
    return size > 1 and dim > 1 and dim % size == 0


def role_partition_spec(mesh, path: str, shape: Tuple[int, ...]):
    """The PartitionSpec the leaf's role wants on ``mesh`` (total: degrades
    to replicated whenever an axis is absent, size 1, or does not divide)."""
    from jax.sharding import PartitionSpec

    fsdp = int(mesh.shape.get("fsdp", 1))
    tensor = int(mesh.shape.get("tensor", 1))
    role = classify_param(path, shape)
    if role == REPLICATED or (fsdp <= 1 and tensor <= 1):
        return PartitionSpec()

    spec: list = [None] * len(shape)
    if role == EMBEDDING:
        # rows (vocab) over the fsdp×tensor product when it divides; else
        # whichever single axis does; embedding dim stays replicated
        rows = shape[0]
        if _divides(rows, fsdp * tensor) and fsdp > 1 and tensor > 1:
            spec[0] = ("fsdp", "tensor")
        elif _divides(rows, fsdp):
            spec[0] = "fsdp"
        elif _divides(rows, tensor):
            spec[0] = "tensor"
        return PartitionSpec(*spec)

    # kernels: tensor on the output (last) dim, fsdp on the largest
    # remaining divisible dim (deterministic tie-break: lower index wins)
    if _divides(shape[-1], tensor):
        spec[-1] = "tensor"
    if fsdp > 1:
        order = sorted(range(len(shape)), key=lambda i: (-shape[i], i))
        for i in order:
            if spec[i] is None and _divides(shape[i], fsdp):
                spec[i] = "fsdp"
                break
    return PartitionSpec(*spec)


#: the remat policy vocabulary (RDT_TRAIN_REMAT / FlaxEstimator remat=)
REMAT_MODES = ("none", "dots", "full")


def remat_policy(mode: str):
    """The ``jax.checkpoint`` saveable policy for one remat mode — the
    activation-side mirror of the parameter role policy above. Roles split
    the forward's residuals the same way they split the weights:

    - ``dots`` keeps the MXU-bound products — the outputs of kernel and
      embedding contractions (:data:`KERNEL`/:data:`EMBEDDING` leaves are
      exactly the operands of those dots) — and recomputes the cheap
      elementwise glue (:data:`REPLICATED`-role bias adds, activations,
      norms) in the backward;
    - ``full`` saves nothing: every residual recomputes, trading the most
      FLOPs for the smallest live-activation footprint;
    - ``none`` returns None — the caller skips ``jax.checkpoint`` entirely
      and XLA keeps all residuals (the fastest, fattest default).
    """
    import jax

    if mode == "none":
        return None
    if mode == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    if mode == "full":
        return jax.checkpoint_policies.nothing_saveable
    raise ValueError(
        f"unknown remat mode {mode!r}: expected one of {REMAT_MODES}")


def apply_remat(fn, mode: str):
    """``fn`` wrapped in ``jax.checkpoint`` under ``mode``'s policy
    (``none`` returns ``fn`` untouched). Applied to the train-step forward
    so the whole per-microbatch activation set obeys the policy."""
    import jax

    policy = remat_policy(mode)
    if policy is None:
        return fn
    return jax.checkpoint(fn, policy=policy)


def describe_roles(tree) -> dict:
    """Debug/bench helper: path → (role, shape) for every leaf of ``tree``."""
    import jax

    out = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        path_str = "/".join(
            str(getattr(p, "key", getattr(p, "name", p))) for p in path)
        shape = tuple(getattr(leaf, "shape", ()))
        out[path_str] = (classify_param(path_str, shape), shape)
    return out


def addressable_nbytes(tree) -> int:
    """Bytes of ``tree`` actually resident on THIS process's devices —
    replicated leaves count one copy per addressable device (that IS the
    memory they occupy), sharded leaves only their local shards. The number
    the fsdp-vs-replicated HBM headroom claim is measured in."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is not None:
            total += sum(s.data.nbytes for s in shards)
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total
