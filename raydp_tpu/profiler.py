"""Tracing/profiling subsystem.

The reference has **no** tracer or profiler hooks anywhere (SURVEY.md §5:
"Tracing / profiling: none" — its only timing code is an unreported wall-clock
helper in examples/pytorch_dlrm.ipynb). This module is deliberately beyond
parity:

- :func:`trace` — a span context manager usable in any session process (driver,
  ETL executor, SPMD rank); spans buffer process-locally with zero contention
  beyond a lock append.
- :func:`collect_chrome_trace` — merges the driver's spans with every live
  actor's (fetched over actor RPC) into one Chrome ``chrome://tracing`` /
  Perfetto JSON, one "process" lane per actor role.
- :func:`jax_trace` — wraps ``jax.profiler.trace`` so device-level XLA traces
  (TensorBoard format) land in the session directory next to the span trace.

The ETL executor wraps task execution in a span and the Flax estimator wraps
each epoch, so an unmodified user program already yields a usable timeline.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from raydp_tpu import knobs

_lock = threading.Lock()
# bounded ring: long-lived actors trace every task (etl/executor.py), so an
# unbounded list would grow for the life of the process; oldest spans drop
MAX_SPANS = int(knobs.get("RDT_PROFILER_MAX_SPANS"))
_spans: "collections.deque[Dict[str, Any]]" = collections.deque(
    maxlen=MAX_SPANS)
_enabled = True


def set_enabled(value: bool) -> None:
    global _enabled
    _enabled = value


@contextlib.contextmanager
def trace(name: str, category: str = "app", **args):
    """Record a wall-clock span around the body (no-op when disabled)."""
    if not _enabled:
        yield
        return
    start = time.time_ns()
    try:
        yield
    finally:
        end = time.time_ns()
        span = {
            "name": name,
            "cat": category,
            "ts": start // 1000,          # chrome trace wants microseconds
            "dur": (end - start) // 1000,
            "ph": "X",
            "tid": threading.get_ident() % 1_000_000,
        }
        if args:
            span["args"] = {k: str(v) for k, v in args.items()}
        with _lock:
            _spans.append(span)


def spans() -> List[Dict[str, Any]]:
    with _lock:
        return list(_spans)


def clear() -> None:
    with _lock:
        _spans.clear()


def _label_spans(span_list: List[Dict[str, Any]], role: str,
                 pid: int) -> List[Dict[str, Any]]:
    out = []
    for s in span_list:
        s = dict(s)
        s["pid"] = pid
        out.append(s)
    out.append({"name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": role}})
    return out


def collect_chrome_trace(path: Optional[str] = None,
                         include_actors: bool = True) -> str:
    """Write a merged Chrome-trace JSON; returns the output path.

    The driver's spans get pid 0; each live actor contributes its buffer as a
    separate pid lane (actors expose it through the ``__rdt_spans__``
    intrinsic). Dead actors' spans are lost — collect before teardown."""
    events = _label_spans(spans(), "driver", 0)

    from raydp_tpu.runtime import head as head_mod

    session_dir = "/tmp/raydp_tpu"
    if head_mod.runtime_initialized():
        rt = head_mod.get_runtime()
        session_dir = rt.session_dir
        if include_actors:
            from raydp_tpu.runtime.actor import ActorHandle
            pid = 1
            for aid, rec in list(rt.records.items()):
                if rec.state != "ALIVE":
                    continue
                role = rec.spec.name or aid
                try:
                    handle = ActorHandle(aid, rec.spec.name, rt.server.address)
                    actor_spans = handle.call("__rdt_spans__", timeout=10.0)
                    events.extend(_label_spans(actor_spans, role, pid))
                except Exception:
                    pass
                pid += 1

    if path is None:
        os.makedirs(os.path.join(session_dir, "traces"), exist_ok=True)
        path = os.path.join(session_dir, "traces", "trace.json")
    else:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
    return path


@contextlib.contextmanager
def jax_trace(log_dir: Optional[str] = None):
    """Capture an XLA device trace (TensorBoard profile) around the body."""
    import jax

    if log_dir is None:
        from raydp_tpu.runtime import head as head_mod
        base = (head_mod.get_runtime().session_dir
                if head_mod.runtime_initialized() else "/tmp/raydp_tpu")
        log_dir = os.path.join(base, "traces", "jax")
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
