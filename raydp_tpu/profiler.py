"""Causal tracing/profiling subsystem.

The reference has **no** tracer or profiler hooks anywhere (SURVEY.md §5:
"Tracing / profiling: none" — its only timing code is an unreported wall-clock
helper in examples/pytorch_dlrm.ipynb). This module is deliberately beyond
parity, and since the observability PR the spans are **causal**, not just
per-process lanes:

- :func:`trace` — a span context manager usable in any session process
  (driver, ETL executor, serve replica, SPMD rank). Every span carries a
  ``trace_id`` and its parent span id through a ``contextvars`` context:
  a top-level driver span mints a fresh trace, ``runtime/rpc.py`` ships the
  active ``(trace_id, parent_span_id)`` in call metadata, and the server
  dispatcher re-installs it — so an executor task span is the *child* of
  the driver stage that submitted it. Thread handoffs that contextvars
  cannot follow (streaming-task threads, the serve dispatcher/worker/
  prefetcher chain) :func:`capture` the context explicitly and
  :func:`activate` it on the other side.
- :func:`collect_chrome_trace` — merges the driver's spans with every live
  actor's (``__rdt_spans__`` intrinsic) and node agent's into one Chrome
  ``chrome://tracing`` / Perfetto JSON: one "process" lane per role, named
  thread lanes (stable per-process thread ids), **flow events**
  (``ph:"s"/"f"``) drawn for every cross-process parent→child link, and
  per-process clock offsets measured against each peer (``__rdt_clock__``
  round-trip handshake) so the merged timeline is aligned to the driver's
  clock — see doc/observability.md for the method and its limits.
- :func:`jax_trace` — wraps ``jax.profiler.trace`` so device-level XLA
  traces (TensorBoard format) land in the session directory next to the
  span trace.

Span/metric/event *names* are registered in ``raydp_tpu/metrics.py`` and
statically checked by rdtlint's ``telemetry-registry`` rule; the registry
also feeds the generated tables in doc/observability.md.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import os
import secrets
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from raydp_tpu import faults, knobs, metrics

_lock = threading.Lock()
# bounded ring: long-lived actors trace every task (etl/executor.py), so an
# unbounded list would grow for the life of the process; oldest spans drop —
# loudly: the drop count rides the metrics registry and the trace metadata
MAX_SPANS = int(knobs.get("RDT_PROFILER_MAX_SPANS"))
_spans: "collections.deque[Dict[str, Any]]" = collections.deque(
    maxlen=MAX_SPANS)
_dropped = 0  # guarded-by: _lock
_enabled = True

#: the active (trace_id, parent_span_id) of this task of execution; None =
#: no trace yet (the next top-level span mints one)
_ctx: "contextvars.ContextVar[Optional[Tuple[str, str]]]" = \
    contextvars.ContextVar("rdt_trace", default=None)

# ---- stable thread ids -------------------------------------------------------
# threading.get_ident() % 1e6 collided across recycled idents and told the
# viewer nothing; instead each thread gets a stable small id on first span
# and its NAME is recorded for Chrome thread_name metadata
_tid_lock = threading.Lock()
_tids: Dict[int, int] = {}        # guarded-by: _tid_lock (ident -> stable)
_tid_names: Dict[int, str] = {}   # guarded-by: _tid_lock (stable -> name)


def _stable_tid() -> int:
    ident = threading.get_ident()
    name = threading.current_thread().name
    with _tid_lock:
        tid = _tids.get(ident)
        if tid is not None and _tid_names.get(tid) != name:
            # the OS recycled a dead thread's ident for a DIFFERENT thread:
            # reusing the cached id would render this thread's spans in a
            # lane labeled with the dead thread's name
            tid = None
        if tid is None:
            tid = len(_tid_names) + 1
            _tids[ident] = tid
            _tid_names[tid] = name
        return tid


def thread_names() -> Dict[int, str]:
    """stable tid → thread name, for the Chrome thread_name metadata."""
    with _tid_lock:
        return dict(_tid_names)


def set_enabled(value: bool) -> None:
    global _enabled
    _enabled = value


# ---- trace context -----------------------------------------------------------

def _new_id() -> str:
    return secrets.token_hex(8)


def current_trace() -> Optional[Tuple[str, str]]:
    """The active ``(trace_id, span_id)`` pair, or None. This is what
    ``runtime/rpc.py`` injects into call metadata."""
    return _ctx.get()


#: explicit-handoff alias: worker threads, completion callbacks, and queue
#: consumers cannot inherit contextvars — they ``capture()`` on the
#: submitting side and ``activate()`` on theirs
capture = current_trace


@contextlib.contextmanager
def activate(ctx: Optional[Tuple[str, str]]):
    """Install a captured/remote trace context for the body (no-op on
    None, so legacy callers without metadata dispatch unchanged)."""
    if not ctx:
        yield
        return
    token = _ctx.set((str(ctx[0]), str(ctx[1])))
    try:
        yield
    finally:
        _ctx.reset(token)


def _append(span: Dict[str, Any]) -> None:
    global _dropped
    with _lock:
        if len(_spans) == _spans.maxlen:
            _dropped += 1
            dropped = True
        else:
            dropped = False
        _spans.append(span)
    if dropped:
        metrics.inc("profiler_spans_dropped_total")


def open_span(name: str, category: str = "app",
              parent: Optional[Tuple[str, str]] = None,
              **args) -> Dict[str, Any]:
    """Start a span WITHOUT entering a context (async lifetimes: a serving
    request whose completion happens on another thread). Pair with
    :func:`close_span`; the span's own context for child propagation is
    ``span_context(span)``. Does not touch the contextvar. Honors
    :func:`set_enabled` like :func:`trace`: when disabled it returns a
    no-op span that ``close_span`` discards and whose context is None."""
    if not _enabled:
        return {"_noop": True}
    ctx = parent if parent is not None else _ctx.get()
    sid = _new_id()
    if ctx is None:
        tr, par = _new_id(), None
    else:
        tr, par = ctx[0], ctx[1]
    span = {
        "name": name,
        "cat": category,
        "ts": time.time_ns() // 1000,  # chrome trace wants microseconds
        "ph": "X",
        "tid": _stable_tid(),
        "sid": sid,
        "tr": tr,
    }
    if par is not None:
        span["par"] = par
    if args:
        span["args"] = {k: str(v) for k, v in args.items()}
    return span


def span_context(span: Dict[str, Any]) -> Optional[Tuple[str, str]]:
    """The (trace_id, span_id) children of this span should activate
    (None for a disabled-profiler no-op span)."""
    if span.get("_noop"):
        return None
    return (span["tr"], span["sid"])


def close_span(span: Dict[str, Any], **args) -> None:
    """Finish an :func:`open_span` span and record it (idempotent: the
    second close of a race loses silently)."""
    if span.get("_closed") or span.get("_noop"):
        return
    span["_closed"] = True
    span["dur"] = max(0, time.time_ns() // 1000 - span["ts"])
    if args:
        span.setdefault("args", {}).update(
            {k: str(v) for k, v in args.items()})
    rec = {k: v for k, v in span.items() if k != "_closed"}
    _append(rec)


@contextlib.contextmanager
def trace(name: str, category: str = "app", **args):
    """Record a wall-clock span around the body (no-op when disabled).

    The span joins the active trace as a child (minting a fresh trace_id
    when there is none — every driver-initiated action's root span is such
    a mint) and becomes the parent of any span opened inside the body,
    including across RPC boundaries."""
    if not _enabled:
        yield
        return
    span = open_span(name, category, **args)
    token = _ctx.set(span_context(span))
    try:
        yield
    finally:
        _ctx.reset(token)
        close_span(span)


def spans() -> List[Dict[str, Any]]:
    with _lock:
        return list(_spans)


def spans_dropped() -> int:
    with _lock:
        return _dropped


def clear() -> None:
    global _dropped
    with _lock:
        _spans.clear()
        _dropped = 0


def export_spans() -> Dict[str, Any]:
    """The ``__rdt_spans__`` intrinsic payload: spans + thread names + the
    drop count + this process's wall clock (offset alignment)."""
    return {"spans": spans(), "threads": thread_names(),
            "dropped": spans_dropped(), "clock_ns": time.time_ns(),
            "pid": os.getpid()}


# the flight recorder wants every fired fault as an event; faults.py is a
# stdlib-only bootstrap module, so IT exposes a hook and the first import of
# this module (any process running runtime code) arms it
faults.set_fire_hook(
    lambda site, key, action: (
        metrics.inc("faults_injected_total", label=site),
        metrics.record_event("fault_injected", site=site, key=key,
                             action=action)))


# ---- chrome trace merge ------------------------------------------------------

class TracePath(str):
    """The collect result: the output path, plus the collection health a
    caller should check before trusting the picture."""

    actors: int = 0
    skipped_actors: int = 0
    flow_events: int = 0
    spans_dropped: int = 0
    clock_offsets_us: Dict[str, int]


def _label_spans(span_list: List[Dict[str, Any]], role: str, pid: int,
                 threads: Optional[Dict] = None,
                 offset_us: int = 0) -> List[Dict[str, Any]]:
    out = []
    for s in span_list:
        s = dict(s)
        s["pid"] = pid
        if offset_us:
            s["ts"] = int(s["ts"]) - offset_us
        out.append(s)
    out.append({"name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": role}})
    for tid, tname in (threads or {}).items():
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": int(tid), "args": {"name": tname}})
    return out


def measure_clock_offset(call, samples: int = 3) -> int:
    """Offset (µs) of a peer's wall clock relative to ours, from ``samples``
    ``__rdt_clock__``-style round trips: the estimate with the smallest RTT
    wins (midpoint method — accurate to ~RTT/2, see doc/observability.md).
    ``call()`` must return the peer's ``time.time_ns()``."""
    best_rtt = None
    best_off = 0
    for _ in range(max(1, samples)):
        t0 = time.time_ns()
        remote = int(call())
        t1 = time.time_ns()
        rtt = t1 - t0
        if best_rtt is None or rtt < best_rtt:
            best_rtt = rtt
            best_off = remote - (t0 + t1) // 2
    return best_off // 1000


def _flow_events(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Chrome flow-event pairs (``ph:"s"`` at the parent, ``ph:"f"`` at the
    child) for every parent→child span link that crosses a process lane —
    the causal arrows the merged timeline exists for."""
    by_sid: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        sid = ev.get("sid")
        if sid:
            by_sid[sid] = ev
    flows: List[Dict[str, Any]] = []
    for ev in events:
        par = ev.get("par")
        if not par:
            continue
        parent = by_sid.get(par)
        if parent is None or parent.get("pid") == ev.get("pid"):
            continue
        flow_id = int(ev["sid"], 16)
        # the start ts is clamped into the parent span so viewers bind it;
        # the finish lands at the child span's start
        start_ts = min(max(int(ev["ts"]), int(parent["ts"])),
                       int(parent["ts"]) + int(parent.get("dur", 0)))
        common = {"name": "trace", "cat": "flow", "id": flow_id}
        flows.append(dict(common, ph="s", pid=parent["pid"],
                          tid=parent["tid"], ts=start_ts))
        flows.append(dict(common, ph="f", bp="e", pid=ev["pid"],
                          tid=ev["tid"], ts=int(ev["ts"])))
    return flows


def collect_chrome_trace(path: Optional[str] = None,
                         include_actors: bool = True) -> TracePath:
    """Write a merged Chrome-trace JSON; returns the output path (a
    :class:`TracePath` carrying collection health: actors reached/skipped,
    flow-event count, span drops).

    The driver's spans get pid 0; each live actor contributes its buffer as
    a separate pid lane (the ``__rdt_spans__`` intrinsic), node agents
    through their ``telemetry`` RPC. Per-peer clock offsets are measured at
    collect time (``__rdt_clock__`` round trips) and actor timestamps are
    shifted onto the driver's clock before the merge. Dead actors' spans
    are lost — collect before teardown; unreachable ones are COUNTED
    (``skipped_actors``), so a half-empty trace is distinguishable from a
    healthy one."""
    events = _label_spans(spans(), "driver", 0, thread_names())
    actors = skipped = 0
    offsets: Dict[str, int] = {}
    dropped = {"driver": spans_dropped()}

    from raydp_tpu.runtime import head as head_mod

    session_dir = "/tmp/raydp_tpu"
    if head_mod.runtime_initialized():
        rt = head_mod.get_runtime()
        session_dir = rt.session_dir
        if include_actors:
            from raydp_tpu.runtime.actor import ActorHandle
            pid = 1
            for aid, rec in list(rt.records.items()):
                if rec.state != "ALIVE":
                    continue
                if not rec.ready.is_set():
                    # mid-restart: resolving would park on the 60 s
                    # ready-waiter grace — telemetry skips NOW, counted
                    skipped += 1
                    pid += 1
                    continue
                role = rec.spec.name or aid
                try:
                    handle = ActorHandle(aid, rec.spec.name,
                                         rt.server.address)
                    offset_us = measure_clock_offset(
                        lambda h=handle: h.call("__rdt_clock__",
                                                timeout=10.0))
                    payload = handle.call("__rdt_spans__", timeout=10.0)
                except Exception:  # noqa: BLE001 - dying actor: skip, COUNT
                    skipped += 1
                    pid += 1
                    continue
                if isinstance(payload, dict):  # current wire format
                    actor_spans = payload.get("spans", [])
                    threads = payload.get("threads", {})
                    dropped[role] = int(payload.get("dropped", 0))
                else:  # a peer running the pre-causal profiler
                    actor_spans, threads = payload, {}
                events.extend(_label_spans(actor_spans, role, pid, threads,
                                           offset_us))
                offsets[role] = offset_us
                actors += 1
                pid += 1
            for node_id, agent in list(getattr(rt, "node_agents",
                                               {}).items()):
                role = f"agent-{node_id}"
                try:
                    offset_us = measure_clock_offset(
                        lambda a=agent: a.call("clock_ns", timeout=10.0))
                    payload = agent.call("telemetry", timeout=10.0)
                except Exception:  # noqa: BLE001 - same skip contract
                    skipped += 1
                    pid += 1
                    continue
                events.extend(_label_spans(
                    payload.get("spans", []), role, pid,
                    payload.get("threads", {}), offset_us))
                offsets[role] = offset_us
                dropped[role] = int(payload.get("dropped", 0))
                actors += 1
                pid += 1
    if skipped:
        metrics.inc("telemetry_skipped_processes_total", skipped)

    flows = _flow_events(events)
    events.extend(flows)

    if path is None:
        os.makedirs(os.path.join(session_dir, "traces"), exist_ok=True)
        path = os.path.join(session_dir, "traces", "trace.json")
    else:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as fh:
        json.dump({
            "traceEvents": events,
            "displayTimeUnit": "ms",
            # a truncated or half-collected trace must announce itself
            "otherData": {
                "skipped_actors": skipped,
                "spans_dropped": dropped,
                "clock_offsets_us": offsets,
                "flow_events": len(flows),
            },
        }, fh)
    out = TracePath(path)
    out.actors = actors
    out.skipped_actors = skipped
    out.flow_events = len(flows)
    out.spans_dropped = sum(dropped.values())
    out.clock_offsets_us = offsets
    return out


@contextlib.contextmanager
def jax_trace(log_dir: Optional[str] = None):
    """Capture an XLA device trace (TensorBoard profile) around the body."""
    import jax

    if log_dir is None:
        from raydp_tpu.runtime import head as head_mod
        base = (head_mod.get_runtime().session_dir
                if head_mod.runtime_initialized() else "/tmp/raydp_tpu")
        log_dir = os.path.join(base, "traces", "jax")
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
