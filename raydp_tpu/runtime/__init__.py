"""raydp_tpu.runtime — the built-in actor runtime substrate.

The reference delegates its substrate to Ray core: actors, named-actor lookup,
placement groups, the plasma shared-memory object store, cross-language calls, and
actor restart (SURVEY.md §1 L1; reference RayExecutorUtils.java:37-62 configures
``maxRestarts=-1`` executor actors). This package provides the same primitives
natively, designed for the TPU process model (one JAX process owns a host's chips,
so placement is host-granular):

- :mod:`rpc` — length-prefixed cloudpickle request/response over TCP.
- :mod:`object_store` — shared-memory Arrow object store with object ownership.
- :mod:`actor` — actor processes, handles, named lookup, restart protocol.
- :mod:`head` — driver-side control plane: registry, nodes, placement groups.
"""

from raydp_tpu.runtime.head import (
    RuntimeContext,
    init_runtime,
    shutdown_runtime,
    get_runtime,
    runtime_initialized,
)
from raydp_tpu.runtime.actor import ActorHandle, actor_context, current_actor_context
from raydp_tpu.runtime.cluster_resources import ClusterResources
from raydp_tpu.runtime.object_store import ObjectRef, ObjectStoreClient
from raydp_tpu.runtime.placement import PlacementGroup, PlacementStrategy

__all__ = [
    "ClusterResources",
    "RuntimeContext",
    "init_runtime",
    "shutdown_runtime",
    "get_runtime",
    "runtime_initialized",
    "ActorHandle",
    "actor_context",
    "current_actor_context",
    "ObjectRef",
    "ObjectStoreClient",
    "PlacementGroup",
    "PlacementStrategy",
]
