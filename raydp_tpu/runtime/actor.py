"""Actors: subprocesses hosting a user object behind the RPC server.

Parity: Ray actors as the reference uses them — named actors resolvable from any
process (``ray.get_actor("raydp-executor-<id>")``, dataset.py:70-78), creation with
``maxRestarts=-1`` / ``maxConcurrency`` (RayExecutorUtils.java:37-62), detection of
"I was restarted" inside the actor (``wasCurrentActorRestarted``,
RayDPExecutor.scala:82-94), and deliberate-kill vs crash-restart distinction
(ApplicationInfo.scala:119-130).

An actor process is spawned as ``python -m raydp_tpu.runtime.actor_main`` with the
head address in env; it fetches its pickled spec from the head, instantiates the
class, serves its methods over :class:`~raydp_tpu.runtime.rpc.RpcServer`, and
reports its bound address back. Handles resolve name→address through the head and
transparently re-resolve after a restart.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import cloudpickle

from raydp_tpu.runtime.rpc import ConnectionLost, RpcClient

# actor lifecycle states
PENDING = "PENDING"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


@dataclass
class ActorSpec:
    actor_id: str
    name: Optional[str]
    cls_bytes: bytes                      # cloudpickled class
    args_bytes: bytes                     # cloudpickled (args, kwargs)
    resources: Dict[str, float] = field(default_factory=dict)
    max_restarts: int = 0                 # -1 = infinite (RayExecutorUtils.java:58)
    max_concurrency: int = 2              # RayExecutorUtils.java:60
    env: Dict[str, str] = field(default_factory=dict)
    node_id: Optional[str] = None
    placement_group_id: Optional[str] = None
    bundle_index: Optional[int] = None


class ActorContext:
    """Process-local context available to code running inside an actor."""

    def __init__(self, actor_id: str, name: Optional[str], node_id: str,
                 was_restarted: bool, restart_count: int, head_client: RpcClient,
                 session_id: str):
        self.actor_id = actor_id
        self.name = name
        self.node_id = node_id
        self.was_restarted = was_restarted
        self.restart_count = restart_count
        self.head = head_client
        self.session_id = session_id


_actor_context: Optional[ActorContext] = None


def actor_context(ctx: Optional[ActorContext]) -> None:
    global _actor_context
    _actor_context = ctx


def current_actor_context() -> Optional[ActorContext]:
    """None when called from the driver; the context inside an actor process."""
    return _actor_context


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method: str):
        self._handle = handle
        self._method = method

    def __call__(self, *args, **kwargs):
        return self._handle.call(self._method, *args, **kwargs)

    def submit(self, *args, **kwargs) -> Future:
        return self._handle.submit(self._method, *args, **kwargs)


class ActorHandle:
    """Client-side handle; picklable (re-resolves through the head on unpickle)."""

    def __init__(self, actor_id: str, name: Optional[str], head_address):
        self.actor_id = actor_id
        self.name = name
        self._head_address = tuple(head_address)
        self._lock = threading.Lock()
        self._head: Optional[RpcClient] = None
        self._client: Optional[RpcClient] = None
        self._address = None

    # -- pickling: drop live sockets ------------------------------------------
    def __getstate__(self):
        return {"actor_id": self.actor_id, "name": self.name,
                "_head_address": self._head_address}

    def __setstate__(self, state):
        self.actor_id = state["actor_id"]
        self.name = state["name"]
        self._head_address = tuple(state["_head_address"])
        self._lock = threading.Lock()
        self._head = None
        self._client = None
        self._address = None

    def _head_client(self) -> RpcClient:
        if self._head is None or self._head._closed:
            self._head = RpcClient(self._head_address)
        return self._head

    def _resolve(self, refresh: bool = False) -> RpcClient:
        with self._lock:
            if self._client is not None and not refresh and not self._client._closed:
                return self._client
            try:
                address = self._head_client().call(
                    "get_actor_address", self.actor_id, timeout=60.0)
            except ConnectionLost:
                # transient head-connection reset: retry once, fresh socket
                # (lock already held — do not route through _head_call)
                if self._head is not None:
                    self._head.close()
                    self._head = None
                address = self._head_client().call(
                    "get_actor_address", self.actor_id, timeout=60.0)
            if address is None:
                raise ConnectionLost(
                    f"actor {self.name or self.actor_id} is not alive")
            if self._client is not None:
                self._client.close()
            self._address = tuple(address)
            self._client = RpcClient(self._address)
            return self._client

    def call(self, method: str, *args, timeout: Optional[float] = None, **kwargs):
        """Synchronous call; one transparent retry after restart-driven reconnect."""
        try:
            return self._resolve().call(method, *args, timeout=timeout, **kwargs)
        except ConnectionLost:
            client = self._resolve(refresh=True)
            return client.call(method, *args, timeout=timeout, **kwargs)

    def submit(self, method: str, *args, **kwargs) -> Future:
        try:
            return self._resolve().submit(method, *args, **kwargs)
        except ConnectionLost:
            return self._resolve(refresh=True).submit(method, *args, **kwargs)

    def __getattr__(self, item: str) -> ActorMethod:
        if item.startswith("_"):
            raise AttributeError(item)
        return ActorMethod(self, item)

    def _head_call(self, method: str, *args,
                   timeout: Optional[float] = None):
        """Head calls from handles are idempotent registry reads/commands; a
        transient connection reset (rare but observed under churn) is retried
        once over a fresh connection instead of failing the caller."""
        try:
            return self._head_client().call(method, *args, timeout=timeout)
        except ConnectionLost:
            with self._lock:
                if self._head is not None:
                    self._head.close()
                    self._head = None
            return self._head_client().call(method, *args, timeout=timeout)

    def state(self) -> str:
        return self._head_call("get_actor_state", self.actor_id)

    def kill(self, no_restart: bool = True) -> None:
        """Deliberate kill — distinguished from a crash so the supervisor does not
        revive it (parity: ApplicationInfo.scala:119-130 kill/retry pathology)."""
        self._head_call("kill_actor", self.actor_id, no_restart)

    def wait_ready(self, timeout: float = 120.0) -> "ActorHandle":
        import time as _time

        deadline = _time.monotonic() + timeout
        try:
            self._head_client().call("wait_actor_ready", self.actor_id,
                                     timeout, timeout=timeout + 10.0)
        except ConnectionLost:
            # transient reset: retry with only the REMAINING budget so the
            # caller's timeout contract holds
            with self._lock:
                if self._head is not None:
                    self._head.close()
                    self._head = None
            remaining = max(1.0, deadline - _time.monotonic())
            self._head_client().call("wait_actor_ready", self.actor_id,
                                     remaining, timeout=remaining + 10.0)
        return self


def dump_spec(cls, args, kwargs) -> tuple:
    return cloudpickle.dumps(cls), cloudpickle.dumps((args, kwargs))
