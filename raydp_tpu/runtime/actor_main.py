"""Actor process bootstrap (``python -m raydp_tpu.runtime.actor_main``).

The spawn handshake mirrors the reference's conn-info protocol — the reference
launches its gateway JVM and reads the bound port back through a temp file
(ray_cluster_master.py:103-183, AppMasterEntryPoint.scala:50-94); here the child
instead reports its bound RPC address to the head over the head's own RPC channel
and fetches its cloudpickled spec. Like the reference's entry point, the process
must die with its supervisor: we watch the head connection and exit when it drops
(AppMasterEntryPoint.scala exits on stdin EOF).
"""

from __future__ import annotations

import os
import sys
import threading
import time

import cloudpickle

from raydp_tpu.log import init_logging
from raydp_tpu.runtime import object_store as objstore
from raydp_tpu.runtime.actor import ActorContext, actor_context
from raydp_tpu.runtime.head import ENV_ACTOR_ID, ENV_HEAD, ENV_SESSION, ENV_SESSION_DIR
from raydp_tpu.runtime.object_store import ObjectStoreClient
from raydp_tpu.runtime.rpc import (
    MethodDispatcher, RpcClient, RpcServer, connect_with_retry,
)


class StoreTableProxy:
    """Forwards ObjectStoreServer's table methods to the head over RPC."""

    def __init__(self, head: RpcClient):
        self._head = head

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        method = f"store_{item}"

        def _call(*args):
            return self._head.call(method, *args)

        return _call


class _ActorServer:
    """Wraps the user object: exposes its public methods plus runtime intrinsics."""

    def __init__(self, instance):
        self._instance = instance
        self._dispatch = MethodDispatcher(instance)

    def __call__(self, method: str, args: tuple, kwargs: dict):
        if method == "__rdt_ping__":
            return "pong"
        if method == "__rdt_shutdown__":
            threading.Thread(target=_delayed_exit, daemon=True).start()
            return True
        if method == "__rdt_spans__":
            from raydp_tpu import profiler
            return profiler.export_spans()
        if method == "__rdt_metrics__":
            from raydp_tpu import metrics
            return metrics.export_state()
        if method == "__rdt_clock__":
            # the driver's clock-offset handshake: this process's wall
            # clock, nothing else — the round trip must stay minimal
            return time.time_ns()
        return self._dispatch(method, args, kwargs)


def _delayed_exit():
    time.sleep(0.2)
    _close_store()
    os._exit(0)


def _close_store():
    """Close this actor process's store client before exit: detaches cached
    segment handles and closes peer payload-host connections, so a graceful
    executor shutdown (or a scale-down cycle) does not strand sockets on the
    node agents it fetched from."""
    try:
        client = objstore._client  # noqa: SLF001 (process-global)
        if client is not None:
            client.close()
    except Exception:
        pass


def main() -> None:
    head_url = os.environ[ENV_HEAD]
    actor_id = os.environ[ENV_ACTOR_ID]
    session_id = os.environ[ENV_SESSION]
    session_dir = os.environ.get(ENV_SESSION_DIR, "/tmp/raydp_tpu")

    host, port = head_url.rsplit(":", 1)
    head = connect_with_retry((host, int(port)))
    spec = head.call("fetch_actor_spec", actor_id)

    name = spec["name"]
    role = name or actor_id
    init_logging(role, spec.get("log_level", "INFO"),
                 os.path.join(session_dir, "logs"), session_id)

    store = ObjectStoreClient(StoreTableProxy(head), session_id,
                              default_owner=name or actor_id)
    objstore.set_client(store)

    ctx = ActorContext(
        actor_id=actor_id,
        name=name,
        node_id=spec["node_id"],
        was_restarted=spec["was_restarted"],
        restart_count=spec["restart_count"],
        head_client=head,
        session_id=session_id,
    )
    actor_context(ctx)

    cls = cloudpickle.loads(spec["cls_bytes"])
    args, kwargs = cloudpickle.loads(spec["args_bytes"])
    instance = cls(*args, **kwargs)

    server = RpcServer(_ActorServer(instance), host="127.0.0.1", port=0,
                       max_concurrency=max(2, int(spec["max_concurrency"])),
                       name=role)
    head.call("actor_ready", actor_id, server.address[0], server.address[1])

    # die with the head: if the driver goes away, so do we
    try:
        while True:
            head.call("ping", timeout=30.0)
            time.sleep(5.0)
    except Exception:
        pass
    finally:
        server.stop()
        _close_store()
        os._exit(0)


if __name__ == "__main__":
    main()
