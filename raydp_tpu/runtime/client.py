"""Client (attach) mode: a driver joining a standalone head's cluster.

Parity: the reference runs its whole test matrix in both direct mode and
Ray-client mode (reference conftest.py:77-140 parametrizes ``ray.init`` vs
``ray.init("ray://...")``), and its data survives driver exit because the Ray
head outlives drivers. Here a standalone head process
(``python -m raydp_tpu.runtime.head --listen``) owns the cluster — actors,
names, placement, and the object-store table — and any number of sequential
or concurrent drivers attach with ``raydp_tpu.init(..., address="host:port")``.
Detaching (or crashing) a driver leaves the head, its actors, and the store
intact; a later driver can resolve the same named actors and read the same
objects (ownership-transferred datasets survive exactly like
``stop_spark(cleanup_data=False)``, reference dataset.py:137-158).

:class:`ClientContext` implements the slice of the RuntimeContext protocol the
rest of the framework uses (``create_actor`` / ``get_actor`` / store client /
session metadata), routed over the head RPC instead of in-process calls.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, Optional

from raydp_tpu.log import get_logger
from raydp_tpu.runtime import object_store as objstore
from raydp_tpu.runtime.actor import ActorHandle, ActorSpec, dump_spec
from raydp_tpu.runtime.object_store import ObjectStoreClient
from raydp_tpu.runtime.rpc import connect_with_retry

logger = get_logger("client")


class _StoreTableProxy:
    """Forwards ObjectStoreServer table methods to the head over RPC (same
    shape as the actor bootstrap's proxy, actor_main.py)."""

    def __init__(self, head):
        self._head = head

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        method = f"store_{item}"

        def _call(*args):
            return self._head.call(method, *args)

        return _call


class _ResourceManagerProxy:
    """The ResourceManager slice a client-mode driver needs — placement
    groups created/removed on the HEAD's resource model over RPC (parity:
    the reference's pg pre-allocation works under Ray client,
    reference context.py:119-140 + conftest.py:77-140)."""

    def __init__(self, head):
        self._head = head

    def create_group(self, bundles, strategy):
        from raydp_tpu.runtime.placement import group_from_dict
        strategy = getattr(strategy, "value", strategy)
        d = self._head.call("create_placement_group", list(bundles),
                            str(strategy), timeout=60.0)
        return group_from_dict(d)

    def get_group(self, group_id: str):
        from raydp_tpu.runtime.placement import group_from_dict
        d = self._head.call("get_placement_group", group_id)
        return group_from_dict(d) if d else None

    def remove_group(self, group_id: str) -> None:
        self._head.call("remove_placement_group", group_id)


class ClientContext:
    """A driver attached to a standalone head. Runtime-protocol compatible
    where the framework needs it; everything rides the head RPC."""

    is_client = True

    def __init__(self, address: str):
        host, port = address.rsplit(":", 1)
        self.address = (host, int(port))
        self.head = connect_with_retry(self.address)
        info = self.head.call("attach_driver",
                              f"driver-{uuid.uuid4().hex[:8]}")
        self.session_id = info["session_id"]
        self.session_dir = info["session_dir"]
        self.driver_id = info["driver_id"]
        #: empty on purpose: records live in the head; locality helpers
        #: degrade gracefully (Session._executor_hosts finds no entries)
        self.records: Dict[str, Any] = {}
        self.resource_manager = _ResourceManagerProxy(self.head)
        self._lock = threading.RLock()

        # data plane: on the head's machine we map its shared memory
        # zero-copy; from another machine we fall back to head-mediated
        # payload RPCs (the store's explicit remote mode)
        same_machine = host in ("127.0.0.1", "localhost") \
            or host == self.head.local_host
        self.store_client = ObjectStoreClient(
            _StoreTableProxy(self.head), self.session_id,
            default_owner=objstore.DRIVER_OWNER,
            remote=not same_machine)
        objstore.set_client(self.store_client)

        # liveness: the head reaps a driver's still-bound actors when its
        # heartbeats stop without a detach (Ray driver-lifetime semantics).
        # The cadence comes from the head (reap window / 4) so a tight
        # window cannot spuriously reap a live-but-slow-beating driver.
        self._beat_interval = float(info.get("heartbeat_interval_s", 5.0))
        self._stopped = threading.Event()
        self._beat_thread = threading.Thread(
            target=self._heartbeat, daemon=True, name="driver-heartbeat")
        self._beat_thread.start()
        logger.info("attached to head at %s (session %s, %s)",
                    address, self.session_id[:12],
                    "same-machine" if same_machine else "remote")

    def _heartbeat(self) -> None:
        from raydp_tpu.runtime.rpc import ConnectionLost
        while not self._stopped.wait(self._beat_interval):
            try:
                known = self.head.call("driver_heartbeat", self.driver_id,
                                       timeout=10.0)
            except ConnectionLost:
                return  # head gone; this client is dead anyway
            except Exception:
                continue  # transient (e.g. busy dispatch pool): keep beating
            if not known:
                # the head already reaped this driver (network stall past the
                # window, or a head restart): say so loudly once and stop —
                # subsequent actor calls will fail, this is the cause
                logger.error(
                    "head no longer recognizes driver %s: this session was "
                    "reaped (heartbeat gap exceeded the head's reap window); "
                    "its actors are gone — re-attach to continue",
                    self.driver_id)
                return

    # ---- actors (the subset RuntimeContext exposes in-process) --------------
    def create_actor(
        self,
        cls,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        *,
        name: Optional[str] = None,
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        max_concurrency: int = 2,
        env: Optional[Dict[str, str]] = None,
        node_id: Optional[str] = None,
        placement_group: Optional[str] = None,
        bundle_index: Optional[int] = None,
        block: bool = True,
    ) -> ActorHandle:
        cls_bytes, args_bytes = dump_spec(cls, args, kwargs or {})
        spec = ActorSpec(
            actor_id=f"actor-{uuid.uuid4().hex[:12]}",
            name=name,
            cls_bytes=cls_bytes,
            args_bytes=args_bytes,
            resources=dict(resources or {}),
            max_restarts=max_restarts,
            max_concurrency=max_concurrency,
            env=dict(env or {}),
            node_id=node_id,
            placement_group_id=placement_group,
            bundle_index=bundle_index,
        )
        actor_id = self.head.call("create_actor", spec.__dict__, False,
                                  self.driver_id, timeout=60.0)
        handle = ActorHandle(actor_id, name, self.address)
        if block:
            handle.wait_ready()
        return handle

    def get_actor(self, name: str) -> Optional[ActorHandle]:
        actor_id = self.head.call("get_named_actor", name)
        if actor_id is None:
            return None
        return ActorHandle(actor_id, name, self.address)

    def store_host_of_node(self, node_id: Optional[str]) -> str:
        return objstore.HEAD_HOST

    def list_nodes(self):
        return self.head.call("list_nodes")

    # ---- lifecycle ----------------------------------------------------------
    def shutdown(self) -> None:
        """Graceful detach: remaining actors are UNBOUND on the head (they
        survive for the next driver); the head and store stay up — this is
        the whole point of attach mode."""
        self._stopped.set()
        try:
            self.head.call("detach_driver", self.driver_id, timeout=10.0)
        except Exception:
            pass
        try:
            self.store_client.close()
        except Exception:
            pass
        objstore.set_client(None)
        try:
            self.head.close()
        except Exception:
            pass
        logger.info("detached from head (session %s)", self.session_id[:12])
