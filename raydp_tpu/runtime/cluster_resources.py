"""Resource-satisfaction query over the runtime's nodes.

Parity: ``ClusterResources`` (reference ray_cluster_resources.py:25-79) — a
cached per-node snapshot with ``satisfy(request)`` returning the node labels
whose *available* resources cover the request, ``total_alive_nodes``, and the
``num_cpus``→``CPU`` key aliasing. Labels are ``node:<address>`` strings like
the reference's ``node:<ip>`` custom resources.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

ITEM_KEYS_MAPPING = {"num_cpus": "CPU", "num_gpus": "GPU"}


class ClusterResources:
    """Per-node availability snapshots, refreshed at most every
    ``refresh_interval`` seconds (reference: 0.1 s class-level cache)."""

    refresh_interval = 0.1

    def __init__(self, runtime=None):
        self._runtime = runtime
        self._lock = threading.Lock()
        self._snapshot: List[Dict] = []
        self._last_refresh = time.monotonic() - self.refresh_interval

    def _rt(self):
        if self._runtime is not None:
            return self._runtime
        from raydp_tpu.runtime import get_runtime
        return get_runtime()

    def _refresh(self) -> None:
        with self._lock:
            now = time.monotonic()
            if now - self._last_refresh < self.refresh_interval:
                return
            self._snapshot = [
                {"node_id": n.node_id, "label": f"node:{n.address}",
                 "available": dict(n.available), "resources": dict(n.resources)}
                for n in self._rt().resource_manager.nodes() if n.alive
            ]
            self._last_refresh = now

    def total_alive_nodes(self) -> int:
        self._refresh()
        return len(self._snapshot)

    def satisfy(self, request: Dict[str, float]) -> List[str]:
        """Labels (``node:<address>``) of nodes whose available resources
        cover ``request`` (keys accept ``num_cpus`` aliasing)."""
        self._refresh()
        out = []
        for node in self._snapshot:
            if self._covers(node["available"], request):
                out.append(node["label"])
        return out

    @staticmethod
    def _covers(available: Dict[str, float], request: Dict[str, float]) -> bool:
        for key, need in request.items():
            key = ITEM_KEYS_MAPPING.get(key, key)
            if available.get(key, 0.0) < need:
                return False
        return True
