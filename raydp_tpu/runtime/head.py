"""The head: driver-side control plane of the actor runtime.

This is the GCS-of-one-process that replaces what the reference gets from Ray's
head services: the named-actor registry, actor supervision/restart, node + resource
accounting, placement groups, and the object-store table (SURVEY.md §1 L1). It runs
as threads inside the driver process; actor processes talk to it over one RPC
connection (address handed down via environment).

Supervision parity: executor actors are created with ``max_restarts=-1`` and revived
on crash (RayExecutorUtils.java:58-59); deliberate kills do not revive
(ApplicationInfo.scala:119-130); a dead owner's objects are swept from the store
unless ownership was transferred (dataset.py:137-158).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import cloudpickle

from raydp_tpu import config as cfg
from raydp_tpu.config import Config
from raydp_tpu.log import get_logger, init_logging
from raydp_tpu.runtime import object_store as objstore
from raydp_tpu.runtime.actor import (
    ALIVE, DEAD, PENDING, RESTARTING, ActorHandle, ActorSpec, dump_spec,
)
from raydp_tpu.runtime.object_store import ObjectStoreClient, ObjectStoreServer
from raydp_tpu.runtime.placement import PlacementGroup, PlacementStrategy, ResourceManager
from raydp_tpu import knobs
from raydp_tpu.runtime.rpc import MethodDispatcher, RpcServer

logger = get_logger("head")

ENV_HEAD = "RAYDP_TPU_HEAD"
ENV_ACTOR_ID = "RAYDP_TPU_ACTOR_ID"
ENV_SESSION = "RAYDP_TPU_SESSION"
ENV_SESSION_DIR = "RAYDP_TPU_SESSION_DIR"


class _RemoteProcess:
    """Popen-shaped handle to a process spawned by a node agent.

    ``poll`` is throttled (one RPC per second per actor) so the supervisor's
    tight loop stays cheap; a lost agent connection reads as exit code -1 with
    ``lost`` set, which the supervisor escalates to node death.
    """

    _POLL_INTERVAL = 1.0

    def __init__(self, agent, pid: int, node_id: str):
        self._agent = agent
        self.pid = pid
        self.node_id = node_id
        self.lost = False
        self._last_poll = 0.0
        self._last_code: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.lost or self._last_code is not None:
            return self._last_code if self._last_code is not None else -1
        now = time.monotonic()
        if now - self._last_poll < self._POLL_INTERVAL:
            return None
        self._last_poll = now
        try:
            code = self._agent.call("poll", self.pid, timeout=10.0)
        except Exception:
            self.lost = True
            self._last_code = -1
            return -1
        if code is not None:
            self._last_code = int(code)
            # the exit is observed exactly once: have the agent harvest the
            # zombie and drop its process-table entry, so an agent that
            # scales executors up and down all day never accumulates dead
            # entries (best-effort — a missed reap only leaks bookkeeping)
            try:
                self._agent.call("reap", self.pid, timeout=10.0)
            except Exception:
                pass
        return self._last_code

    def kill(self) -> None:
        try:
            self._agent.call("kill", self.pid, timeout=10.0)
        except Exception:
            self.lost = True


@dataclass
class ActorRecord:
    spec: ActorSpec
    state: str = PENDING
    process: Optional[Any] = None  # subprocess.Popen or _RemoteProcess
    address: Optional[tuple] = None
    node_id: Optional[str] = None
    restart_count: int = 0
    was_restarted: bool = False
    deliberate_kill: bool = False
    ready: threading.Event = field(default_factory=threading.Event)
    resources_held: Dict[str, float] = field(default_factory=dict)
    #: attach mode: the client driver this actor belongs to. A graceful
    #: detach unbinds (actor survives for the next driver); a driver that
    #: stops heartbeating without detaching gets its actors reaped — the
    #: Ray semantics of non-detached actors dying with their driver.
    driver_id: Optional[str] = None


class HeadService:
    """RPC surface of the head. One instance serves driver helpers and all actors."""

    def __init__(self, runtime: "RuntimeContext"):
        self._rt = runtime

    # ---- object store table (proxied verbatim) ------------------------------
    def store_seal(self, *a):
        return self._rt.store_server.seal(*a)

    def store_seal_batch(self, *a):
        return self._rt.store_server.seal_batch(*a)

    def store_lookup(self, *a):
        return self._rt.store_server.lookup(*a)

    def store_lookup_batch(self, *a):
        return self._rt.store_server.lookup_batch(*a)

    def store_fetch_ranges(self, *a):
        return self._rt.store_server.fetch_ranges(*a)

    def store_op_counts(self, *a):
        return self._rt.store_server.op_counts(*a)

    def store_reset_op_counts(self, *a):
        return self._rt.store_server.reset_op_counts(*a)

    def store_contains(self, *a):
        return self._rt.store_server.contains(*a)

    def store_free(self, *a):
        return self._rt.store_server.free(*a)

    def store_transfer_ownership(self, *a):
        return self._rt.store_server.transfer_ownership(*a)

    def store_free_owned_by(self, *a):
        return self._rt.store_server.free_owned_by(*a)

    def store_stats(self, *a):
        return self._rt.store_server.stats(*a)

    def store_owned_by(self, *a):
        return self._rt.store_server.owned_by(*a)

    def store_arena_info(self, *a):
        return self._rt.store_server.arena_info(*a)

    def store_arena_stats(self, *a):
        return self._rt.store_server.arena_stats(*a)

    def store_arena_reap(self, *a):
        return self._rt.store_server.arena_reap(*a)

    def store_fetch_payload(self, *a):
        return self._rt.store_server.fetch_payload(*a)

    def store_store_payload(self, *a):
        return self._rt.store_server.store_payload(*a)

    def store_locations(self, *a):
        return self._rt.store_server.locations(*a)

    def store_residency(self, *a):
        return self._rt.store_server.residency(*a)

    def store_eviction_hints(self, *a):
        return self._rt.store_server.eviction_hints(*a)

    def store_derive_budgets(self, *a):
        return self._rt.store_server.derive_budgets(*a)

    # pipelined-shuffle seal notifications: poll may return a DeferredReply
    # (the head's RPC server resolves it when events arrive or the poll
    # timeout lapses), so a long-polling reducer never parks a dispatcher
    def store_stream_begin(self, *a):
        return self._rt.store_server.stream_begin(*a)

    def store_stream_publish(self, *a):
        return self._rt.store_server.stream_publish(*a)

    def store_stream_poll(self, *a):
        return self._rt.store_server.stream_poll(*a)

    def store_stream_abort(self, *a):
        return self._rt.store_server.stream_abort(*a)

    def store_stream_close(self, *a):
        return self._rt.store_server.stream_close(*a)

    def register_store_host(self, node_id: str, arena_segment,
                            shm_budget=None):
        """A node agent announces its machine-local payload plane."""
        return self._rt.register_store_host(node_id, arena_segment,
                                            shm_budget)

    # ---- actor lifecycle ----------------------------------------------------
    def fetch_actor_spec(self, actor_id: str) -> Dict[str, Any]:
        rec = self._rt.record(actor_id)
        return {
            "cls_bytes": rec.spec.cls_bytes,
            "args_bytes": rec.spec.args_bytes,
            "name": rec.spec.name,
            "max_concurrency": rec.spec.max_concurrency,
            "node_id": rec.node_id,
            "was_restarted": rec.was_restarted,
            "restart_count": rec.restart_count,
            "session_id": self._rt.session_id,
            "session_dir": self._rt.session_dir,
            "log_level": self._rt.config.get(cfg.LOG_LEVEL_KEY, "INFO"),
        }

    def actor_ready(self, actor_id: str, host: str, port: int) -> None:
        self._rt.on_actor_ready(actor_id, (host, port))

    def get_actor_address(self, actor_id: str):
        rec = self._rt.records.get(actor_id)
        if rec is None or rec.state == DEAD:
            return None
        if rec.ready.is_set():
            return rec.address
        # restart in flight: wait WITHOUT parking this dispatcher thread —
        # the reply completes when the actor reports ready (or 60s grace
        # lapses), so a mass-restart flurry cannot starve unrelated traffic
        return self._rt.add_ready_waiter(actor_id, 60.0, mode="address")

    def get_actor_state(self, actor_id: str) -> str:
        rec = self._rt.records.get(actor_id)
        return rec.state if rec else DEAD

    def wait_actor_ready(self, actor_id: str, timeout: float):
        rec = self._rt.record(actor_id)
        if rec.ready.is_set():
            return True
        return self._rt.add_ready_waiter(actor_id, timeout, mode="ready")

    def get_named_actor(self, name: str) -> Optional[str]:
        """Resolve a LIVE named actor (the in-process ``get_actor`` liveness
        contract: dead actors don't resolve)."""
        handle = self._rt.get_actor(name)
        return handle.actor_id if handle is not None else None

    def create_actor(self, spec_fields: Dict[str, Any], block: bool = False,
                     driver_id: Optional[str] = None) -> str:
        spec = ActorSpec(**spec_fields)
        handle = self._rt.launch_actor(spec, block=block, driver_id=driver_id)
        return handle.actor_id

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        self._rt.kill_actor(actor_id, no_restart)

    def list_actors(self) -> List[Dict[str, Any]]:
        out = []
        for aid, rec in self._rt.records.items():
            out.append({
                "actor_id": aid, "name": rec.spec.name, "state": rec.state,
                "node_id": rec.node_id, "restart_count": rec.restart_count,
                "resources": rec.spec.resources,
            })
        return out

    # ---- nodes / resources / placement --------------------------------------
    def list_nodes(self) -> List[Dict[str, Any]]:
        return [
            {"node_id": n.node_id, "address": n.address, "alive": n.alive,
             "resources": dict(n.resources), "available": dict(n.available)}
            for n in self._rt.resource_manager.nodes()
        ]

    def add_node(self, resources: Dict[str, float], address: Optional[str] = None) -> str:
        return self._rt.resource_manager.add_node(address or "127.0.0.1", resources)

    def remove_node(self, node_id: str) -> None:
        self._rt.remove_node(node_id)

    def register_node_agent(self, host: str, port: int,
                            resources: Dict[str, float],
                            address: str,
                            store_isolated: bool = False) -> Dict[str, Any]:
        """A node agent joins: its machine becomes a schedulable node whose
        actor processes the head spawns through the agent (parity: a Ray
        raylet registering with the GCS, SURVEY.md §1 L1)."""
        return self._rt.register_node_agent(host, port, resources, address,
                                            store_isolated)

    def create_placement_group(self, bundles: List[Dict[str, float]],
                               strategy: str) -> Dict[str, Any]:
        group = self._rt.resource_manager.create_group(
            bundles, PlacementStrategy(strategy))
        return _group_to_dict(group)

    def remove_placement_group(self, group_id: str) -> None:
        self._rt.resource_manager.remove_group(group_id)

    def get_placement_group(self, group_id: str) -> Optional[Dict[str, Any]]:
        group = self._rt.resource_manager.get_group(group_id)
        return _group_to_dict(group) if group else None

    def list_placement_groups(self) -> List[Dict[str, Any]]:
        return [_group_to_dict(g) for g in self._rt.resource_manager.groups()]

    def ping(self) -> str:
        return "pong"

    # ---- attach/client mode -------------------------------------------------
    def attach_driver(self, driver_id: str) -> Dict[str, Any]:
        """A driver joins this (standalone) head as a client — parity with
        Ray-client mode in the reference's test matrix (conftest.py:77-140).
        Names and stored objects belong to the head's session; actors the
        driver creates are bound to it until it detaches (graceful detach
        unbinds them to survive; a crashed driver's actors are reaped after
        its heartbeats stop — the Ray driver-lifetime semantics)."""
        self._rt.register_driver(driver_id)
        return {"session_id": self._rt.session_id,
                "session_dir": self._rt.session_dir,
                "driver_id": driver_id,
                # clients derive their beat cadence from the head's reap
                # window so a small window cannot spuriously reap live
                # drivers that beat too slowly
                "heartbeat_interval_s": max(
                    1.0, self._rt.driver_reap_after_s / 4.0)}

    def driver_heartbeat(self, driver_id: str) -> bool:
        return self._rt.driver_heartbeat(driver_id)

    def detach_driver(self, driver_id: str) -> bool:
        return self._rt.detach_driver(driver_id)


def _terminate(proc) -> None:
    """Kill a local Popen (whole process group) or a remote agent process."""
    if isinstance(proc, _RemoteProcess):
        proc.kill()
        return
    if proc.poll() is None:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            try:
                proc.kill()
            except ProcessLookupError:
                pass


def _group_to_dict(group: PlacementGroup) -> Dict[str, Any]:
    return {
        "group_id": group.group_id,
        "strategy": group.strategy.value,
        "bundles": [
            {"index": b.index, "resources": b.resources, "node_id": b.node_id}
            for b in group.bundles
        ],
    }


class RuntimeContext:
    """Singleton runtime: head services + supervisor + driver-side store client."""

    def __init__(self, config: Optional[Config] = None,
                 virtual_nodes: Optional[List[Dict[str, float]]] = None,
                 listen_host: str = "127.0.0.1", listen_port: int = 0):
        self.config = config or Config()
        self.session_id = uuid.uuid4().hex
        self.session_dir = os.path.join(
            "/tmp", "raydp_tpu", f"session_{self.session_id[:12]}")
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        init_logging("driver", self.config.get(cfg.LOG_LEVEL_KEY, "INFO"),
                     os.path.join(self.session_dir, "logs"), self.session_id)

        arena = self._create_arena()
        # eviction/spill budget: configured value, else the arena capacity
        # (no arena → default arena size); "0" disables spilling
        budget = self.config.get_memory(
            cfg.SPILL_BUDGET_KEY,
            default=(arena.size if arena is not None
                     else _default_arena_size()))
        spill_dir = (self.config.get(cfg.SPILL_DIR_KEY)
                     or os.path.join(self.session_dir, "spill")) \
            if budget > 0 else None
        self.store_server = ObjectStoreServer(
            self.session_id, arena=arena,
            spill_dir=spill_dir, shm_budget=budget or None)
        self.resource_manager = ResourceManager()
        if virtual_nodes:
            for res in virtual_nodes:
                self.resource_manager.add_node("127.0.0.1", res)
        else:
            self.resource_manager.add_node("127.0.0.1", _default_node_resources())

        self.records: Dict[str, ActorRecord] = {}
        self.names: Dict[str, str] = {}
        self.node_agents: Dict[str, Any] = {}  # node_id → agent RpcClient
        self.store_hosts: Dict[str, Optional[str]] = {}  # node_id → arena seg
        # distributed data plane: payloads on agent machines are released /
        # head-mediated-fetched through the owning node's agent RPC
        self.store_server.node_release = self._node_store_release
        self.store_server.node_fetch = self._node_store_fetch
        self.store_server.node_spill = self._node_store_spill
        self.store_server.node_fault_in = self._node_store_fault_in
        self.store_server.node_remove_spill = self._node_store_remove_spill
        self._lock = threading.RLock()
        # guarded-by: _waiters_lock; (deadline, timeout, id, fut, mode)
        self._waiters: List[tuple] = []
        self._waiters_lock = threading.Lock()
        #: attach-mode drivers: driver_id → last heartbeat monotonic time
        self._drivers: Dict[str, float] = {}  # guarded-by: _lock
        self.driver_reap_after_s = float(knobs.get("RDT_DRIVER_REAP_S"))
        #: lazy warm-fork manager for the LOCAL spawn path (1-elem ref so the
        #: shared spawn glue can create it on first use); agents own their own
        self._warm_fork: List[Any] = [None]
        self._stopped = threading.Event()

        self.service = HeadService(self)
        self.server = RpcServer(MethodDispatcher(self.service),
                                host=listen_host, port=listen_port,
                                max_concurrency=16, name="head")
        self.store_client = ObjectStoreClient(self.store_server, self.session_id,
                                              default_owner=objstore.DRIVER_OWNER)
        objstore.set_client(self.store_client)

        self._supervisor = threading.Thread(target=self._supervise, daemon=True,
                                            name="actor-supervisor")
        self._supervisor.start()
        logger.info("runtime head started at %s (session %s)",
                    self.server.url, self.session_id[:12])

    def _create_arena(self):
        """Native store arena, per ``raydp.tpu.object_store.native``:
        ``auto`` (default) uses it when the C++ core builds, ``on`` requires
        it, ``off`` forces per-object segments."""
        mode = (self.config.get(cfg.NATIVE_OBJECT_STORE_KEY, "auto") or
                "auto").strip().lower()
        if mode in ("0", "false", "off", "no"):
            return None
        required = mode in ("1", "true", "on", "yes")
        try:
            from raydp_tpu.native.arena import Arena
            size = self.config.get_memory(
                cfg.OBJECT_STORE_MEMORY_KEY, default=_default_arena_size())
            arena = Arena.create(f"rdt{self.session_id[:8]}_arena", size)
            logger.info("native object store arena: %s (%d MiB)",
                        arena.segment, size >> 20)
            return arena
        except Exception as e:
            if required:
                raise RuntimeError(
                    f"native object store requested but unavailable: {e}") from e
            logger.warning("native store arena unavailable (%s); "
                           "using per-object segments", e)
            return None

    # ---- actor management ---------------------------------------------------
    def record(self, actor_id: str) -> ActorRecord:
        rec = self.records.get(actor_id)
        if rec is None:
            raise KeyError(f"unknown actor {actor_id}")
        return rec

    def create_actor(
        self,
        cls,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        *,
        name: Optional[str] = None,
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        max_concurrency: int = 2,
        env: Optional[Dict[str, str]] = None,
        node_id: Optional[str] = None,
        placement_group: Optional[str] = None,
        bundle_index: Optional[int] = None,
        block: bool = True,
    ) -> ActorHandle:
        cls_bytes, args_bytes = dump_spec(cls, args, kwargs or {})
        spec = ActorSpec(
            actor_id=f"actor-{uuid.uuid4().hex[:12]}",
            name=name,
            cls_bytes=cls_bytes,
            args_bytes=args_bytes,
            resources=dict(resources or {}),
            max_restarts=max_restarts,
            max_concurrency=max_concurrency,
            env=dict(env or {}),
            node_id=node_id,
            placement_group_id=placement_group,
            bundle_index=bundle_index,
        )
        return self.launch_actor(spec, block=block)

    # ---- attach-mode driver lifetime ----------------------------------------
    def register_driver(self, driver_id: str) -> None:
        with self._lock:
            self._drivers[driver_id] = time.monotonic()
        logger.info("driver %s attached", driver_id)

    def driver_heartbeat(self, driver_id: str) -> bool:
        with self._lock:
            if driver_id not in self._drivers:
                return False
            self._drivers[driver_id] = time.monotonic()
            return True

    def detach_driver(self, driver_id: str) -> bool:
        """Graceful detach: the driver's remaining actors are UNBOUND — they
        survive for the next driver (this is what carries the master of a
        ``stop(cleanup_data=False)`` session across drivers)."""
        with self._lock:
            present = self._drivers.pop(driver_id, None) is not None
            for rec in self.records.values():
                if rec.driver_id == driver_id:
                    rec.driver_id = None
        if present:
            logger.info("driver %s detached", driver_id)
        return present

    def _reap_dead_drivers(self) -> None:
        """A driver that stopped heartbeating without detaching crashed: its
        still-bound actors are reaped (Ray's non-detached-actor lifetime),
        so a crashing client cannot leak sessions on a long-lived head."""
        now = time.monotonic()
        with self._lock:
            dead = [d for d, beat in self._drivers.items()
                    if now - beat > self.driver_reap_after_s]
            for d in dead:
                del self._drivers[d]
            victims = [rec.spec.actor_id for rec in self.records.values()
                       if rec.driver_id in dead and rec.state != DEAD] \
                if dead else []
        for d in dead:
            logger.warning("driver %s stopped heartbeating; reaping its "
                           "actors", d)
        for actor_id in victims:
            self.kill_actor(actor_id, no_restart=True)

    def launch_actor(self, spec: ActorSpec, block: bool = True,
                     driver_id: Optional[str] = None) -> ActorHandle:
        if driver_id is not None:
            # a client creating actors is self-evidently alive: re-register
            # it if a heartbeat stall already reaped it, so its new actors
            # stay reapable instead of leaking bound to an unknown driver
            with self._lock:
                if driver_id not in self._drivers:
                    self._drivers[driver_id] = time.monotonic()
                    logger.info("driver %s re-registered via create_actor",
                                driver_id)
        with self._lock:
            if spec.name is not None and spec.name in self.names:
                existing = self.records.get(self.names[spec.name])
                if existing is not None and existing.state != DEAD:
                    raise ValueError(f"actor name {spec.name!r} already taken")
            if spec.placement_group_id is not None and spec.bundle_index is not None:
                # bundle resources were pre-reserved at group creation: run on
                # the bundle's node without charging the node a second time
                # (parity: actors scheduled *into* bundles, context.py:119-140)
                group = self.resource_manager.get_group(spec.placement_group_id)
                if group is None:
                    raise ValueError(
                        f"unknown placement group {spec.placement_group_id}")
                node_id = group.bundle_node(spec.bundle_index)
                held: Dict[str, float] = {}
            else:
                node_id = self.resource_manager.allocate(spec.resources,
                                                         spec.node_id)
                if node_id is None:
                    raise ValueError(
                        f"cannot place actor {spec.name or spec.actor_id}: "
                        f"resources {spec.resources} not available")
                held = dict(spec.resources)
            rec = ActorRecord(spec=spec, node_id=node_id, resources_held=held,
                              driver_id=driver_id)
            self.records[spec.actor_id] = rec
            if spec.name is not None:
                self.names[spec.name] = spec.actor_id
            self._spawn(rec)
        handle = ActorHandle(spec.actor_id, spec.name, self.server.address)
        if block:
            handle.wait_ready()
        return handle

    def _spawn(self, rec: ActorRecord) -> None:
        log_name = (f"{rec.spec.name or rec.spec.actor_id}"
                    f"-r{rec.restart_count}")
        agent = self.node_agents.get(rec.node_id) if rec.node_id else None
        if agent is not None:
            # the node is served by an agent: spawn there (real multi-node
            # placement — node affinity resolves to that machine's processes)
            overrides = dict(rec.spec.env)
            overrides[ENV_HEAD] = self.server.url
            overrides[ENV_ACTOR_ID] = rec.spec.actor_id
            overrides[ENV_SESSION] = self.session_id
            overrides[ENV_SESSION_DIR] = self.session_dir
            # data-plane env (RDT_STORE_HOST_ID / PAYLOAD_ADDR / ARENA) is
            # injected by the agent itself at spawn: children on an isolated
            # node write to and read from that machine's own payload plane
            # forward the driver's import path: cloudpickle pickles classes
            # by reference, so the child must resolve the driver's modules
            # (the agent appends its own path after these)
            driver_path = [p for p in sys.path if p]
            if overrides.get("PYTHONPATH"):
                driver_path.append(overrides["PYTHONPATH"])
            overrides["PYTHONPATH"] = os.pathsep.join(driver_path)
            # one bounded (30s) hop to a peer whose spawn handler never
            # calls back into the head's pool — no self-deadlock feedback
            # rdtlint: allow[dispatcher-blocking] bounded agent spawn hop
            pid = agent.call("spawn", overrides, log_name, timeout=30.0)
            rec.process = _RemoteProcess(agent, pid, rec.node_id)
        else:
            env = dict(os.environ)
            env.update(rec.spec.env)
            env[ENV_HEAD] = self.server.url
            env[ENV_ACTOR_ID] = rec.spec.actor_id
            env[ENV_SESSION] = self.session_id
            env[ENV_SESSION_DIR] = self.session_dir
            # child must resolve every module the driver can (cloudpickle
            # pickles classes by reference): prepend the driver's sys.path
            driver_path = [p for p in sys.path if p]
            existing = env.get("PYTHONPATH")
            if existing:
                driver_path.append(existing)
            env["PYTHONPATH"] = os.pathsep.join(driver_path)
            log_path = os.path.join(self.session_dir, "logs",
                                    f"{log_name}.out")
            proc = None
            if bool(knobs.get("RDT_WARM_FORK")):
                # fork-fast scale-up: clone the pre-imported prototype
                # instead of paying a cold interpreter + import chain; any
                # warm-plane failure falls through to the cold Popen below
                from raydp_tpu.runtime import warm_fork
                proc = warm_fork.warm_spawn(
                    self._warm_fork, os.path.join(self.session_dir, "logs"),
                    env, log_path, log_name)
            if proc is None:
                out = open(log_path, "ab")
                proc = subprocess.Popen(
                    [sys.executable, "-m", "raydp_tpu.runtime.actor_main"],
                    env=env, stdout=out, stderr=subprocess.STDOUT,
                    start_new_session=True,
                )
                out.close()
            rec.process = proc
        rec.state = PENDING if rec.restart_count == 0 else RESTARTING

    def on_actor_ready(self, actor_id: str, address: tuple) -> None:
        rec = self.record(actor_id)
        rec.address = tuple(address)
        rec.state = ALIVE
        rec.ready.set()
        self._resolve_waiters()
        logger.info("actor %s ready at %s (restart %d)",
                    rec.spec.name or actor_id, address, rec.restart_count)

    # ---- non-blocking ready waits -------------------------------------------
    def add_ready_waiter(self, actor_id: str, timeout: float, mode: str):
        """A deferred reply completed by ``on_actor_ready`` / the supervisor
        tick instead of a parked RPC thread. ``mode='address'`` resolves to
        the address or None (get_actor_address contract); ``mode='ready'``
        resolves to True or raises TimeoutError (wait_actor_ready)."""
        from concurrent.futures import Future

        from raydp_tpu.runtime.rpc import DeferredReply

        fut: Future = Future()
        with self._waiters_lock:
            self._waiters.append(
                (time.monotonic() + timeout, timeout, actor_id, fut, mode))
        # the actor may have turned ready between the check and registration
        self._resolve_waiters()
        return DeferredReply(fut)

    def _resolve_waiters(self) -> None:
        now = time.monotonic()
        with self._waiters_lock:
            waiters, self._waiters = self._waiters, []
        keep = []
        for deadline, timeout, actor_id, fut, mode in waiters:
            if fut.done():
                continue
            rec = self.records.get(actor_id)
            if rec is not None and rec.ready.is_set() and rec.state == ALIVE:
                fut.set_result(tuple(rec.address) if mode == "address"
                               else True)
            elif rec is None or rec.state == DEAD:
                if mode == "address":
                    fut.set_result(None)
                else:
                    fut.set_exception(TimeoutError(
                        f"actor {actor_id} died while waiting "
                        f"(state={rec.state if rec else 'unknown'})"))
            elif now >= deadline:
                if mode == "address":
                    fut.set_result(None)
                else:
                    fut.set_exception(TimeoutError(
                        f"actor {rec.spec.name or actor_id} not ready after "
                        f"{timeout}s (state={rec.state})"))
            else:
                keep.append((deadline, timeout, actor_id, fut, mode))
        if keep:
            with self._waiters_lock:
                self._waiters.extend(keep)

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        with self._lock:
            rec = self.records.get(actor_id)
            if rec is None:
                return
            rec.deliberate_kill = no_restart
            proc = rec.process
        if proc is not None:
            _terminate(proc)
        # supervisor loop will observe the exit and apply restart-vs-dead policy

    def owner_key(self, rec: ActorRecord) -> str:
        return rec.spec.name or rec.spec.actor_id

    def _supervise(self) -> None:
        while not self._stopped.is_set():
            try:
                self._supervise_once()
                self._resolve_waiters()
                self._reap_dead_drivers()
            except Exception:  # noqa: BLE001 - the supervisor must never die
                logger.exception("supervisor tick failed; continuing")
            time.sleep(0.1)

    def _supervise_once(self) -> None:
        with self._lock:
            items = list(self.records.items())
        for actor_id, rec in items:
            if rec.state == DEAD or rec.process is None:
                continue
            code = rec.process.poll()
            if code is None:
                continue
            if (isinstance(rec.process, _RemoteProcess)
                    and rec.process.lost):
                # unreachable agent = node death: reap the whole node so
                # every actor on it reroutes, not just this one
                self._agent_lost(rec.process.node_id)
            with self._lock:
                if rec.state == DEAD:
                    continue
                rec.ready.clear()
                rec.address = None
                if rec.node_id and rec.resources_held:
                    self.resource_manager.release(rec.node_id, rec.resources_held)
                    rec.resources_held = {}
                limit = rec.spec.max_restarts
                can_restart = (not rec.deliberate_kill
                               and (limit == -1 or rec.restart_count < limit))
                if can_restart:
                    rec.restart_count += 1
                    rec.was_restarted = True
                    rec.state = RESTARTING
                    node_id, held = self._replacement_node(rec)
                    if node_id is None:
                        # leave RESTARTING: retried next tick (pending resources)
                        rec.process = None
                        continue
                    rec.node_id = node_id
                    rec.resources_held = held
                    logger.warning(
                        "actor %s exited with code %s; restarting (attempt %d)",
                        rec.spec.name or actor_id, code, rec.restart_count)
                    self._spawn_supervised(rec)
                else:
                    rec.state = DEAD
                    rec.process = None
                    logger.info("actor %s exited with code %s; dead",
                                rec.spec.name or actor_id, code)
                    self.store_server.free_owned_by(self.owner_key(rec))
        # pending RESTARTING actors with no process: retry placement — unless
        # a deliberate kill arrived while the record had no process to
        # terminate (e.g. a dead driver's reaped executor awaiting resources):
        # resurrecting it would leak the actor forever
        dead_now: List[ActorRecord] = []
        with self._lock:
            for rec in self.records.values():
                if rec.state == RESTARTING and rec.process is None:
                    if rec.deliberate_kill:
                        rec.state = DEAD
                        dead_now.append(rec)
                        continue
                    node_id, held = self._replacement_node(rec)
                    if node_id is not None:
                        rec.node_id = node_id
                        rec.resources_held = held
                        self._spawn_supervised(rec)
        for rec in dead_now:
            logger.info("actor %s killed while awaiting restart; dead",
                        rec.spec.name or rec.spec.actor_id)
            self.store_server.free_owned_by(self.owner_key(rec))

    def _spawn_supervised(self, rec: ActorRecord) -> None:
        """Spawn from the supervisor thread: a failed spawn (e.g. the target
        node's agent just died) must not kill the supervisor — the record
        stays RESTARTING and is re-placed next tick, and an unreachable
        agent's node is reaped."""
        try:
            self._spawn(rec)
        except Exception as e:  # noqa: BLE001 - supervisor must survive
            from raydp_tpu.runtime.rpc import RemoteError

            logger.warning("spawn of %s on %s failed (%s); will re-place",
                           rec.spec.name or rec.spec.actor_id, rec.node_id, e)
            if (rec.node_id in self.node_agents
                    and not isinstance(e, RemoteError)):
                # transport failure → the agent itself is unreachable
                self._agent_lost(rec.node_id)
            if rec.node_id and rec.resources_held:
                self.resource_manager.release(rec.node_id, rec.resources_held)
                rec.resources_held = {}
            rec.process = None
            rec.state = RESTARTING

    def _replacement_node(self, rec: ActorRecord):
        """Node for a restarting actor: its placement-group bundle if the group
        (and that node) is still alive, else a fresh allocation."""
        spec = rec.spec
        if spec.placement_group_id is not None and spec.bundle_index is not None:
            group = self.resource_manager.get_group(spec.placement_group_id)
            if group is not None:
                node_id = group.bundle_node(spec.bundle_index)
                node = self.resource_manager.get_node(node_id) if node_id else None
                if node is not None and node.alive:
                    return node_id, {}
        node_id = self.resource_manager.allocate(spec.resources, spec.node_id)
        return node_id, (dict(spec.resources) if node_id is not None else {})

    # ---- nodes --------------------------------------------------------------
    def node_is_remote(self, node) -> bool:
        """True when processes on ``node`` cannot map this host's shared
        memory (the node is another machine) — the single source of the
        data-plane locality rule for both actor and SPMD-rank spawns."""
        return node.address not in ("127.0.0.1", self.server.address[0])

    def register_node_agent(self, host: str, port: int,
                            resources: Dict[str, float],
                            address: str,
                            store_isolated: bool = False) -> Dict[str, Any]:
        from raydp_tpu.runtime.rpc import RpcClient

        client = RpcClient((host, int(port)))
        node_id = self.resource_manager.add_node(address, resources)
        node = self.resource_manager.get_node(node_id)
        # another machine cannot share this host's /dev/shm: its agent must
        # host its own payload plane (tests force this with RDT_STORE_ISOLATED)
        isolated = bool(store_isolated) or (node is not None
                                            and self.node_is_remote(node))
        with self._lock:
            self.node_agents[node_id] = client
        logger.info("node agent registered: %s at %s:%d (%s, store=%s)",
                    node_id, host, port, resources,
                    "isolated" if isolated else "shared")
        return {"node_id": node_id, "session_id": self.session_id,
                "session_dir": self.session_dir,
                "store_mode": "isolated" if isolated else "shared"}

    def register_store_host(self, node_id: str,
                            arena_segment: Optional[str],
                            shm_budget: Optional[int] = None) -> bool:
        with self._lock:
            self.store_hosts[node_id] = arena_segment
        self.store_server.register_node_budget(node_id, shm_budget)
        return True

    def store_host_of_node(self, node_id: Optional[str]) -> str:
        """The data-plane host id for processes on ``node_id`` — the node id
        itself when its agent hosts an isolated payload plane, else the head
        machine's shared plane."""
        if node_id is not None and node_id in self.store_hosts:
            return node_id
        return objstore.HEAD_HOST

    def _node_store_release(self, host_id: str, items,
                            defer_segments: bool = False) -> None:
        agent = self.node_agents.get(host_id)
        if agent is not None:
            agent.call("store_release", items, defer_segments, timeout=30.0)

    def _node_store_fetch(self, host_id: str, segment: str, offset: int,
                          size: int) -> bytes:
        agent = self.node_agents.get(host_id)
        if agent is None:
            raise KeyError(f"node {host_id} is gone; payload unreadable")
        return agent.call("store_fetch", segment, offset, size, timeout=60.0)

    def _node_store_spill(self, host_id: str, object_id: str, segment: str,
                          offset: int, size: int) -> bool:
        agent = self.node_agents.get(host_id)
        if agent is None:
            raise KeyError(f"node {host_id} is gone")
        return agent.call("store_spill", object_id, segment, offset, size,
                          timeout=120.0)

    def _node_store_fault_in(self, host_id: str, object_id: str,
                             seg_name: str):
        agent = self.node_agents.get(host_id)
        if agent is None:
            raise KeyError(f"node {host_id} is gone")
        return agent.call("store_fault_in", object_id, seg_name,
                          timeout=120.0)

    def _node_store_remove_spill(self, host_id: str, object_ids) -> None:
        agent = self.node_agents.get(host_id)
        if agent is not None:
            agent.call("store_remove_spill", list(object_ids), timeout=30.0)

    def _agent_lost(self, node_id: str) -> None:
        agent = self.node_agents.pop(node_id, None)
        if agent is None:
            return
        try:
            agent.close()
        except Exception:
            pass
        logger.warning("node agent for %s unreachable; removing node", node_id)
        self.remove_node(node_id)

    def _purge_node_store(self, node_id: str) -> None:
        """Node death: its payload plane is gone — drop its table entries so
        readers fail into lineage recovery instead of timing out."""
        with self._lock:
            hosted = self.store_hosts.pop(node_id, "__absent__")
        if hosted != "__absent__":
            self.store_server.purge_host(node_id)

    def remove_node(self, node_id: str) -> None:
        """Fault injection: node death kills its actors; restartable actors are
        revived on surviving nodes (parity: test_spark_cluster.py:262-299)."""
        self._purge_node_store(node_id)
        self.resource_manager.remove_node(node_id)
        with self._lock:
            victims = [rec for rec in self.records.values()
                       if rec.node_id == node_id and rec.state != DEAD]
        for rec in victims:
            rec.spec.node_id = None  # allow re-placement anywhere
            self.kill_actor(rec.spec.actor_id, no_restart=False)

    def get_actor(self, name: str) -> Optional[ActorHandle]:
        actor_id = self.names.get(name)
        if actor_id is None:
            return None
        rec = self.records.get(actor_id)
        if rec is None or rec.state == DEAD:
            return None
        return ActorHandle(actor_id, name, self.server.address)

    # ---- shutdown -----------------------------------------------------------
    def shutdown(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        with self._lock:
            recs = list(self.records.values())
        for rec in recs:
            rec.deliberate_kill = True
            if rec.process is not None:
                _terminate(rec.process)
            rec.state = DEAD
        self._resolve_waiters()  # every record is DEAD now: fail the waiters
        if self._warm_fork[0] is not None:
            # after the workers above are terminated: the prototype's death
            # cascades (pdeathsig) to any forked worker still exiting
            try:
                self._warm_fork[0].stop()
            except Exception:
                pass
        self.store_client.close()
        # store shutdown BEFORE agent teardown: node-hosted payload releases
        # ride the still-open agent connections
        self.store_server.shutdown()
        for node_id, agent in list(self.node_agents.items()):
            try:
                agent.call("shutdown", timeout=5.0)
            except Exception:
                pass
            try:
                agent.close()
            except Exception:
                pass
        self.node_agents.clear()
        self.server.stop()
        objstore.set_client(None)
        logger.info("runtime head shut down (session %s)", self.session_id[:12])


def _default_arena_size() -> int:
    """Default arena capacity: a quarter of /dev/shm free space, capped at 4 GiB
    and floored at 64 MiB (objects overflowing the arena fall back to dedicated
    segments, so undersizing degrades gracefully)."""
    try:
        st = os.statvfs("/dev/shm")
        free = st.f_bavail * st.f_frsize
    except OSError:
        free = 1 << 30
    return max(64 << 20, min(4 << 30, free // 4))


def _default_node_resources() -> Dict[str, float]:
    try:
        import psutil
        mem = int(psutil.virtual_memory().total * 0.8)
    except Exception:
        mem = 8 << 30
    cpus = float(os.cpu_count() or 1)
    return {"CPU": max(cpus, 4.0), "memory": float(mem)}


# -- module-global singleton --------------------------------------------------------
_runtime: Optional[RuntimeContext] = None
_runtime_lock = threading.RLock()


def init_runtime(config: Optional[Config] = None,
                 virtual_nodes: Optional[List[Dict[str, float]]] = None) -> RuntimeContext:
    global _runtime
    with _runtime_lock:
        if _runtime is None:
            _runtime = RuntimeContext(config=config, virtual_nodes=virtual_nodes)
        return _runtime


def adopt_runtime(rt) -> None:
    """Install a runtime-protocol object as the process-global runtime — the
    attach path (``raydp_tpu.init(address=...)`` installs a
    :class:`~raydp_tpu.runtime.client.ClientContext`)."""
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            raise RuntimeError("runtime already initialized in this process")
        _runtime = rt


def get_runtime() -> RuntimeContext:
    if _runtime is None:
        raise RuntimeError("runtime not initialized; call raydp_tpu.init() first")
    return _runtime


def runtime_initialized() -> bool:
    return _runtime is not None


def shutdown_runtime() -> None:
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None


def main() -> None:
    """Standalone head: a cluster that outlives (and is shared by) drivers.

    ``python -m raydp_tpu.runtime.head --listen [--port N] [--host H]``
    prints ``RDT_HEAD_READY <host:port>`` once serving; drivers attach with
    ``raydp_tpu.init(app, address="host:port")``. Parity: the Ray head node
    the reference's client-mode matrix connects to (conftest.py:77-140) and
    the driver-outliving cluster of test_spark_cluster.py:113-134."""
    import argparse

    ap = argparse.ArgumentParser(
        description="raydp_tpu standalone head (attach/client mode)")
    ap.add_argument("--listen", action="store_true", required=True,
                    help="serve until killed")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 picks a free port (printed on stdout)")
    ap.add_argument("--cpus", type=float, default=None,
                    help="CPU resource of the head node (default: all)")
    args = ap.parse_args()

    virtual_nodes = None
    if args.cpus is not None:
        virtual_nodes = [{"CPU": args.cpus,
                          "memory": _default_node_resources()["memory"]}]
    rt = RuntimeContext(listen_host=args.host, listen_port=args.port,
                        virtual_nodes=virtual_nodes)
    print(f"RDT_HEAD_READY {rt.server.url}", flush=True)
    try:
        while True:
            time.sleep(3600.0)
    except KeyboardInterrupt:
        pass
    finally:
        rt.shutdown()


if __name__ == "__main__":
    main()
