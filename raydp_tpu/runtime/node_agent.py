"""Node agent: the per-machine daemon that gives the head real multi-node
placement (``python -m raydp_tpu.runtime.node_agent --head HOST:PORT``).

This supplies the substrate role Ray's raylet plays for the reference (SURVEY.md
§1 L1; the reference adopts real node/raylet addresses in
ray_cluster_master.py:185-203): it registers the machine as a node with the
head, then spawns/polls/kills actor processes on request, so ``node_id``
affinity and placement-group bundles resolve to real processes on the agent's
machine instead of bookkeeping entries at 127.0.0.1. The head supervises the
agent connection; an unreachable agent is node death — its actors are killed
from the records and restartable ones revive on surviving nodes.

Object-store note: actor processes attach the session's shared-memory segments
directly, so agents on the *same* machine share the data plane zero-copy.
Agents on other machines carry control-plane traffic over the same RPC; bulk
payload reads from a remote store segment go through the head's table server.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

from raydp_tpu.log import get_logger, init_logging
from raydp_tpu.runtime.rpc import MethodDispatcher, RpcServer, connect_with_retry

logger = get_logger("node_agent")


try:  # load libc at import: CDLL inside a post-fork preexec_fn can
    # deadlock/fail silently in a threaded parent (malloc locks)
    import ctypes

    _LIBC = ctypes.CDLL("libc.so.6", use_errno=True)
except Exception:  # pragma: no cover - non-glibc platform
    _LIBC = None


def _die_with_parent():
    """PR_SET_PDEATHSIG: actor processes die with their agent, the way a
    node's workers die with its raylet — killing the agent IS node death,
    and no orphan keeps serving a stale actor address. Runs between fork and
    exec; must only make async-signal-safe calls (the prctl syscall is)."""
    if _LIBC is not None:
        _LIBC.prctl(1, signal.SIGKILL)  # 1 = PR_SET_PDEATHSIG


class NodeAgentService:
    """RPC surface the head drives: spawn/poll/kill actor processes here."""

    def __init__(self, agent: "NodeAgent"):
        self._agent = agent

    def spawn(self, env_overrides: Dict[str, str], log_name: str,
              argv: Optional[list] = None) -> int:
        return self._agent.spawn(env_overrides, log_name, argv)

    def poll(self, pid: int) -> Optional[int]:
        return self._agent.poll(pid)

    def kill(self, pid: int) -> bool:
        return self._agent.kill(pid)

    def list_pids(self) -> Dict[int, Optional[int]]:
        return {pid: self._agent.poll(pid) for pid in list(self._agent.procs)}

    def shutdown(self) -> bool:
        threading.Thread(target=self._agent.stop, daemon=True).start()
        return True

    def ping(self) -> str:
        return "pong"


class NodeAgent:
    def __init__(self, head_url: str, resources: Dict[str, float],
                 log_dir: Optional[str] = None):
        self.head_url = head_url
        self.resources = resources
        host, port = head_url.rsplit(":", 1)
        self.head = connect_with_retry((host, int(port)))
        self.server = RpcServer(MethodDispatcher(NodeAgentService(self)),
                                host=self.head.local_host, port=0,
                                max_concurrency=8, name="node-agent")
        self.procs: Dict[int, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()

        reply = self.head.call(
            "register_node_agent", self.server.address[0],
            self.server.address[1], dict(resources), self.head.local_host)
        self.node_id = reply["node_id"]
        self.session_id = reply["session_id"]
        self.session_dir = reply["session_dir"]
        self.log_dir = log_dir or os.path.join(self.session_dir, "logs")
        os.makedirs(self.log_dir, exist_ok=True)
        logger.info("node agent %s registered with %s (resources=%s)",
                    self.node_id, head_url, resources)

    # ---- process management (driven by the head) ----------------------------
    def spawn(self, env_overrides: Dict[str, str], log_name: str,
              argv: Optional[list] = None) -> int:
        """Spawn a runtime process here; ``argv`` defaults to the actor
        bootstrap but callers may launch other entry points (e.g. SPMD gang
        ranks, ``raydp_tpu.spmd.worker``). An override valued ``None`` removes
        the variable from the child env (same contract as the local spawn
        path, SPMDJob._spawn_rank)."""
        env = dict(os.environ)
        for k, v in env_overrides.items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = v
        # the child resolves driver-pickled classes by reference: the head's
        # forwarded PYTHONPATH (driver sys.path) takes precedence — matching
        # local-spawn semantics so one session never runs two code versions —
        # with this agent's own import path appended as fallback
        paths = ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        paths.extend(p for p in sys.path if p)
        env["PYTHONPATH"] = os.pathsep.join(paths)
        log_path = os.path.join(self.log_dir, f"{log_name}.out")
        out = open(log_path, "ab")
        cmd = [sys.executable] + (list(argv) if argv
                                  else ["-m", "raydp_tpu.runtime.actor_main"])
        proc = subprocess.Popen(
            cmd, env=env, stdout=out, stderr=subprocess.STDOUT,
            start_new_session=True, preexec_fn=_die_with_parent)
        out.close()
        with self._lock:
            self.procs[proc.pid] = proc
        logger.info("spawned actor process %d (%s)", proc.pid, log_name)
        return proc.pid

    def poll(self, pid: int) -> Optional[int]:
        with self._lock:
            proc = self.procs.get(pid)
        if proc is None:
            return -1  # unknown pid: report dead
        return proc.poll()

    def kill(self, pid: int) -> bool:
        with self._lock:
            proc = self.procs.get(pid)
        if proc is None or proc.poll() is not None:
            return False
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            try:
                proc.kill()
            except ProcessLookupError:
                pass
        return True

    # ---- lifecycle ----------------------------------------------------------
    def serve_forever(self) -> None:
        """Heartbeat the head; die (reaping children) when it goes away."""
        try:
            while not self._stopped.is_set():
                self.head.call("ping", timeout=30.0)
                time.sleep(2.0)
        except Exception:
            logger.warning("head connection lost; shutting down")
        finally:
            self.stop()

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        with self._lock:
            procs = list(self.procs.values())
        for proc in procs:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    try:
                        proc.kill()
                    except ProcessLookupError:
                        pass
        self.server.stop()
        logger.info("node agent %s stopped", self.node_id)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="raydp_tpu node agent: joins a head as a schedulable node")
    ap.add_argument("--head", required=True, help="head RPC address host:port")
    ap.add_argument("--cpus", type=float, default=float(os.cpu_count() or 4))
    ap.add_argument("--memory", type=float, default=None,
                    help="bytes; default 80%% of RAM")
    ap.add_argument("--resource", action="append", default=[],
                    metavar="NAME=AMOUNT",
                    help="extra custom resource (repeatable)")
    ap.add_argument("--log-dir", default=None)
    args = ap.parse_args()

    mem = args.memory
    if mem is None:
        try:
            import psutil
            mem = float(int(psutil.virtual_memory().total * 0.8))
        except Exception:
            mem = float(8 << 30)
    resources = {"CPU": args.cpus, "memory": mem}
    for item in args.resource:
        name, _, amount = item.partition("=")
        resources[name] = float(amount or 1.0)

    init_logging("node-agent", os.environ.get("RDT_LOG_LEVEL", "INFO"),
                 None, None)
    agent = NodeAgent(args.head, resources, log_dir=args.log_dir)
    agent.serve_forever()


if __name__ == "__main__":
    main()
