"""Node agent: the per-machine daemon that gives the head real multi-node
placement (``python -m raydp_tpu.runtime.node_agent --head HOST:PORT``).

This supplies the substrate role Ray's raylet plays for the reference (SURVEY.md
§1 L1; the reference adopts real node/raylet addresses in
ray_cluster_master.py:185-203): it registers the machine as a node with the
head, then spawns/polls/kills actor processes on request, so ``node_id``
affinity and placement-group bundles resolve to real processes on the agent's
machine instead of bookkeeping entries at 127.0.0.1. The head supervises the
agent connection; an unreachable agent is node death — its actors are killed
from the records and restartable ones revive on surviving nodes.

Object-store note: the agent is also its machine's payload plane in the
distributed data plane. Agents on the head's machine share the head's
shared-memory segments zero-copy; an agent on ANOTHER machine (or forced with
``RDT_STORE_ISOLATED=1``) runs its own :class:`PayloadHost` — a node-local
arena/segment namespace where its actors write payloads, served to readers on
other machines with one direct RPC (never through the head). Parity: the
per-node plasma store a raylet hosts for the reference
(RayDPExecutor.scala:271-287 ``getBlockLocations``).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

from raydp_tpu import knobs
from raydp_tpu.log import get_logger, init_logging
from raydp_tpu.runtime.rpc import MethodDispatcher, RpcServer, connect_with_retry

logger = get_logger("node_agent")


try:  # load libc at import: CDLL inside a post-fork preexec_fn can
    # deadlock/fail silently in a threaded parent (malloc locks)
    import ctypes

    _LIBC = ctypes.CDLL("libc.so.6", use_errno=True)
except Exception:  # pragma: no cover - non-glibc platform
    _LIBC = None


def _die_with_parent():
    """PR_SET_PDEATHSIG: actor processes die with their agent, the way a
    node's workers die with its raylet — killing the agent IS node death,
    and no orphan keeps serving a stale actor address. Runs between fork and
    exec; must only make async-signal-safe calls (the prctl syscall is)."""
    if _LIBC is not None:
        _LIBC.prctl(1, signal.SIGKILL)  # 1 = PR_SET_PDEATHSIG


class NodeAgentService:
    """RPC surface the head drives: spawn/poll/kill actor processes here."""

    def __init__(self, agent: "NodeAgent"):
        self._agent = agent

    def spawn(self, env_overrides: Dict[str, str], log_name: str,
              argv: Optional[list] = None) -> int:
        return self._agent.spawn(env_overrides, log_name, argv)

    def poll(self, pid: int) -> Optional[int]:
        return self._agent.poll(pid)

    def kill(self, pid: int) -> bool:
        return self._agent.kill(pid)

    def reap(self, pid: int) -> Optional[int]:
        """Kill ``pid`` (if still running) and, once it has exited, harvest
        the zombie and drop it from the process table — the scale-down
        reaper's bookkeeping twin of :meth:`kill`, which leaves a dead
        entry behind forever. Returns the exit code, or None while the
        process has not exited yet (callers poll; this handler never parks
        a dispatcher waiting on an exit)."""
        return self._agent.reap(pid)

    def list_pids(self) -> Dict[int, Optional[int]]:
        return {pid: self._agent.poll(pid) for pid in list(self._agent.procs)}

    def shutdown(self) -> bool:
        threading.Thread(target=self._agent.stop, daemon=True).start()
        return True

    def ping(self) -> str:
        return "pong"

    # ---- telemetry (doc/observability.md) -----------------------------------
    def telemetry(self):
        """This agent process's full observability state — spans, thread
        names, metrics, and flight-recorder events — the node-agent twin of
        the actor ``__rdt_spans__`` intrinsic, for trace collection."""
        from raydp_tpu import metrics, profiler
        out = profiler.export_spans()
        out.update(metrics.export_state())
        return out

    def metrics_state(self):
        """Metrics + events only (``__rdt_metrics__`` twin) — what the
        metrics/blackbox harvests want; the span ring (up to
        RDT_PROFILER_MAX_SPANS entries) would be pure transfer weight
        there and megabytes of dead JSON in a blackbox bundle."""
        from raydp_tpu import metrics
        return metrics.export_state()

    def clock_ns(self) -> int:
        """The driver's clock-offset handshake (``__rdt_clock__`` twin)."""
        return time.time_ns()

    # ---- node-local payload plane (isolated store mode) ---------------------
    def store_fetch(self, segment: str, offset: int, size: int) -> bytes:
        """Serve payload bytes hosted on this machine to a reader elsewhere —
        the one-hop node-to-node transfer of the distributed data plane."""
        return self._agent.payload_host.fetch(segment, offset, size)

    def store_fetch_ranges(self, items) -> list:
        """Many byte ranges of payloads hosted here in ONE RPC — the batched
        reduce-side read of the consolidated shuffle path: a reduce task
        fetches its bucket's slice of every map output on this machine with
        a single round-trip instead of one per blob. Each item is
        ``(segment, base, start, size)``: the payload's table offset (arena
        offset, -1 for a dedicated segment) and the range offset within it."""
        return [self._agent.payload_host.fetch_range(seg, int(base),
                                                     int(start), int(size))
                for seg, base, start, size in items]

    def store_release(self, items, defer_segments: bool = False) -> int:
        return self._agent.payload_host.release(
            [(seg, int(off)) for seg, off in items],
            defer_segments=defer_segments)

    def store_reap(self) -> bool:
        return self._agent.payload_host.reap()

    def store_arena_info(self):
        return self._agent.payload_host.arena_info()

    def store_arena_stats(self):
        return self._agent.payload_host.arena_stats()

    # ---- node-local eviction/spill (head-directed) --------------------------
    def store_spill(self, object_id: str, segment: str, offset: int,
                    size: int) -> bool:
        """Copy a payload hosted here to this machine's spill dir (the head
        owns the table and the LRU decision; the bytes never leave the node).
        The shm is NOT released here — the head releases it exactly once,
        after confirming the table entry survived the write (a concurrent
        free() would otherwise double-release the same arena offset)."""
        agent = self._agent
        data = agent.payload_host.fetch(segment, int(offset), int(size))
        os.makedirs(agent.spill_dir, exist_ok=True)
        path = os.path.join(agent.spill_dir, object_id)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return True

    def store_fault_in(self, object_id: str, seg_name: str):
        """Bring a spilled payload back into this machine's shm; returns the
        new ``(segment, offset)``. The spill file is kept — the head removes
        it only after its table commits the new location (a lost reply must
        leave the object recoverable)."""
        agent = self._agent
        path = os.path.join(agent.spill_dir, object_id)
        with open(path, "rb") as f:
            data = f.read()
        return agent.payload_host.write(data, seg_name)

    def store_remove_spill(self, object_ids) -> int:
        n = 0
        for oid in object_ids:
            try:
                os.remove(os.path.join(self._agent.spill_dir, oid))
                n += 1
            except OSError:
                pass
        return n


class NodeAgent:
    def __init__(self, head_url: str, resources: Dict[str, float],
                 log_dir: Optional[str] = None):
        self.head_url = head_url
        self.resources = resources
        host, port = head_url.rsplit(":", 1)
        self.head = connect_with_retry((host, int(port)))
        self.server = RpcServer(MethodDispatcher(NodeAgentService(self)),
                                host=self.head.local_host, port=0,
                                max_concurrency=8, name="node-agent")
        self.procs: Dict[int, subprocess.Popen] = {}
        self._lock = threading.Lock()
        #: lazy per-agent warm-fork manager (1-elem ref for the shared glue)
        self._warm_fork: list = [None]
        self._stopped = threading.Event()

        store_isolated = bool(knobs.get("RDT_STORE_ISOLATED"))
        reply = self.head.call(
            "register_node_agent", self.server.address[0],
            self.server.address[1], dict(resources), self.head.local_host,
            store_isolated)
        self.node_id = reply["node_id"]
        self.session_id = reply["session_id"]
        self.session_dir = reply["session_dir"]
        self.log_dir = log_dir or os.path.join(self.session_dir, "logs")
        os.makedirs(self.log_dir, exist_ok=True)

        # distributed data plane: on another machine (or when forced for
        # tests) this agent hosts its own payload plane — node-local arena +
        # segments, served over this agent's RPC
        from raydp_tpu.runtime.object_store import PayloadHost
        self.store_isolated = reply.get("store_mode") == "isolated"
        self.payload_host = PayloadHost(
            self._create_arena() if self.store_isolated else None)
        self.spill_dir = os.path.join(self.session_dir,
                                      f"spill-{self.node_id}")
        if self.store_isolated:
            info = self.payload_host.arena_info()
            # this machine's shm budget: objects past it LRU-spill to the
            # node's spill dir under the head's direction
            budget = knobs.get("RDT_NODE_SHM_BUDGET")
            if budget is None:
                budget = info["size"] if info else (1 << 30)
            budget = int(budget)
            self.head.call("register_store_host", self.node_id,
                           info["segment"] if info else None, budget)
        logger.info("node agent %s registered with %s (resources=%s, store=%s)",
                    self.node_id, head_url, resources,
                    "isolated" if self.store_isolated else "shared")

    def _create_arena(self):
        """Node-local arena for this machine's payloads; per-object segment
        fallback when the native core is unavailable."""
        try:
            from raydp_tpu.native.arena import Arena
            from raydp_tpu.runtime.head import _default_arena_size
            size = knobs.get("RDT_NODE_ARENA_SIZE")
            size = int(size) if size is not None else _default_arena_size()
            arena = Arena.create(f"rdt{self.session_id[:8]}_n{os.getpid()}",
                                 size)
            logger.info("node-local store arena: %s (%d MiB)",
                        arena.segment, size >> 20)
            return arena
        except Exception as e:
            logger.warning("node arena unavailable (%s); per-object segments",
                           e)
            return None

    # ---- process management (driven by the head) ----------------------------
    def spawn(self, env_overrides: Dict[str, str], log_name: str,
              argv: Optional[list] = None) -> int:
        """Spawn a runtime process here; ``argv`` defaults to the actor
        bootstrap but callers may launch other entry points (e.g. SPMD gang
        ranks, ``raydp_tpu.spmd.worker``). An override valued ``None`` removes
        the variable from the child env (same contract as the local spawn
        path, SPMDJob._spawn_rank)."""
        env = dict(os.environ)
        for k, v in env_overrides.items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = v
        if self.store_isolated:
            # children write payloads into THIS machine's plane and read
            # same-machine objects zero-copy; explicit overrides win
            from raydp_tpu.runtime import object_store as objstore
            info = self.payload_host.arena_info()
            defaults = {
                objstore.ENV_STORE_HOST_ID: self.node_id,
                objstore.ENV_STORE_PAYLOAD_ADDR:
                    f"{self.server.address[0]}:{self.server.address[1]}",
            }
            if info:
                defaults[objstore.ENV_STORE_ARENA] = info["segment"]
            for k, v in defaults.items():
                if k not in env_overrides:
                    env[k] = v
        # the child resolves driver-pickled classes by reference: the head's
        # forwarded PYTHONPATH (driver sys.path) takes precedence — matching
        # local-spawn semantics so one session never runs two code versions —
        # with this agent's own import path appended as fallback
        paths = ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        paths.extend(p for p in sys.path if p)
        env["PYTHONPATH"] = os.pathsep.join(paths)
        log_path = os.path.join(self.log_dir, f"{log_name}.out")
        proc = None
        if argv is None and bool(knobs.get("RDT_WARM_FORK")):
            # fork-fast scale-up for the default actor bootstrap only (SPMD
            # ranks and other entry points keep their exec semantics); any
            # warm-plane failure falls through to the cold Popen below
            from raydp_tpu.runtime import warm_fork
            proc = warm_fork.warm_spawn(self._warm_fork, self.log_dir,
                                        env, log_path, log_name)
        if proc is None:
            out = open(log_path, "ab")
            cmd = [sys.executable] + (
                list(argv) if argv
                else ["-m", "raydp_tpu.runtime.actor_main"])
            proc = subprocess.Popen(
                cmd, env=env, stdout=out, stderr=subprocess.STDOUT,
                start_new_session=True, preexec_fn=_die_with_parent)
            out.close()
        with self._lock:
            self.procs[proc.pid] = proc
        logger.info("spawned actor process %d (%s)", proc.pid, log_name)
        return proc.pid

    def poll(self, pid: int) -> Optional[int]:
        with self._lock:
            proc = self.procs.get(pid)
        if proc is None:
            return -1  # unknown pid: report dead
        return proc.poll()

    def kill(self, pid: int) -> bool:
        with self._lock:
            proc = self.procs.get(pid)
        if proc is None or proc.poll() is not None:
            return False
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            try:
                proc.kill()
            except ProcessLookupError:
                pass
        return True

    def reap(self, pid: int) -> Optional[int]:
        """Kill + harvest: SIGKILL the group if still alive, then (without
        blocking) poll; once exited, the Popen's poll() has waitpid'ed the
        zombie and the table entry is dropped so a long-lived agent that
        scales executors up and down all day never accumulates dead
        entries. Returns the exit code, None while still exiting."""
        with self._lock:
            proc = self.procs.get(pid)
        if proc is None:
            return -1
        self.kill(pid)
        code = proc.poll()
        if code is not None:
            with self._lock:
                self.procs.pop(pid, None)
        return code

    # ---- lifecycle ----------------------------------------------------------
    def serve_forever(self) -> None:
        """Heartbeat the head; die (reaping children) when it goes away."""
        try:
            while not self._stopped.is_set():
                self.head.call("ping", timeout=30.0)
                time.sleep(2.0)
        except Exception:
            logger.warning("head connection lost; shutting down")
        finally:
            self.stop()

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        with self._lock:
            procs = list(self.procs.values())
        for proc in procs:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    try:
                        proc.kill()
                    except ProcessLookupError:
                        pass
        if self._warm_fork[0] is not None:
            try:
                self._warm_fork[0].stop()
            except Exception:
                pass
        self.server.stop()
        try:
            self.payload_host.shutdown()
        except Exception:
            pass
        import shutil
        shutil.rmtree(self.spill_dir, ignore_errors=True)
        logger.info("node agent %s stopped", self.node_id)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="raydp_tpu node agent: joins a head as a schedulable node")
    ap.add_argument("--head", required=True, help="head RPC address host:port")
    ap.add_argument("--cpus", type=float, default=float(os.cpu_count() or 4))
    ap.add_argument("--memory", type=float, default=None,
                    help="bytes; default 80%% of RAM")
    ap.add_argument("--resource", action="append", default=[],
                    metavar="NAME=AMOUNT",
                    help="extra custom resource (repeatable)")
    ap.add_argument("--log-dir", default=None)
    args = ap.parse_args()

    mem = args.memory
    if mem is None:
        try:
            import psutil
            mem = float(int(psutil.virtual_memory().total * 0.8))
        except Exception:
            mem = float(8 << 30)
    resources = {"CPU": args.cpus, "memory": mem}
    for item in args.resource:
        name, _, amount = item.partition("=")
        resources[name] = float(amount or 1.0)

    init_logging("node-agent", str(knobs.get("RDT_LOG_LEVEL")), None, None)
    agent = NodeAgent(args.head, resources, log_dir=args.log_dir)
    agent.serve_forever()


if __name__ == "__main__":
    main()
