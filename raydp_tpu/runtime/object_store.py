"""Shared-memory object store: the plasma-equivalent data plane.

The reference's data plane is Ray's plasma store: Spark executors serialize Arrow IPC
partitions into shared memory, Python training workers map them zero-copy, and an
ownership protocol decides lifetime (SURVEY.md §2.5; reference
RayDPUtils.java:45-53 ``readBinary`` is the zero-copy handoff kernel;
dataset.py:137-158 transfers object ownership to the master actor so data outlives
Spark). This module provides the native equivalent:

- every object is one POSIX shared-memory segment (``/dev/shm``), written once and
  sealed; readers attach and get a zero-copy ``memoryview``;
- a metadata server (thread in the head process) keeps the object table:
  ``id -> (segment, size, kind, owner)``;
- objects are *owned*: when their owning actor dies un-transferred, they are freed;
  ``transfer_ownership`` re-homes them (parity with ``get_raydp_master_owner``,
  dataset.py:137-158);
- Arrow payloads round-trip as IPC streams so a reader can decode a table without
  copying the body buffers (``pa.ipc.open_stream(pa.py_buffer(view))``).

Payload layout has two modes behind the same client API:

- **native arena** (default when the C++ core builds): all payloads live in one
  session-wide shared-memory segment carved by the C++ slab allocator
  (``csrc/store/arena.cpp``, bound via :mod:`raydp_tpu.native.arena`). Writers
  ``rdt_alloc`` from any process; readers attach the one segment once and slice
  zero-copy views — one mmap per process instead of one per object;
- **per-object segments** (fallback): each object is its own ``/dev/shm``
  segment, written once and sealed.

The metadata entry records ``offset >= 0`` for arena-resident payloads.
"""

from __future__ import annotations

import collections
import os
import secrets
import threading
import time as _time_mod
from concurrent.futures import Future
from dataclasses import dataclass, field
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle
import pyarrow as pa

from raydp_tpu import faults, metrics
from raydp_tpu.log import get_logger
from raydp_tpu import knobs
from raydp_tpu.runtime.rpc import DeferredReply

logger = get_logger("object_store")


class ObjectLostError(KeyError):
    """A store blob is gone or unreachable: the table has no entry (freed,
    owner died, host purged) or the payload plane cannot serve it. Typed so
    the ETL engine can tell this apart from deterministic application errors
    (which fail fast) and route it into lineage recovery — retrying the
    consumer task would just replay the miss until the retry budget burns.

    Carried across processes as ``RemoteError.exc_type == "ObjectLostError"``
    with the 32-hex object id embedded in the message, which is how the
    driver learns *which* blob to regenerate."""

    def __init__(self, object_id: str, detail: str = ""):
        msg = f"object {object_id} lost from store"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)
        self.object_id = object_id
        # flight recorder: constructed exactly at loss-detection sites, so
        # recording here covers every raise path (local KeyError translate,
        # RPC-proxied RemoteError, vanished segment, dead payload host)
        try:
            metrics.inc("store_objects_lost_total")
            metrics.record_event("object_lost", oid=object_id, detail=detail)
        except Exception:  # noqa: BLE001 - telemetry never masks the loss
            pass

    # not KeyError.__str__: loss messages must not render repr-quoted in
    # logs, RemoteError.message, and ObjectsLostError text
    __str__ = Exception.__str__


class ShuffleStreamAborted(RuntimeError):
    """A pipelined-shuffle seal stream ended without completing: its map
    stage failed (the driver published an abort) or the stage was closed /
    never began (a drain-abandoned reducer polling after its action ended).
    Deterministic from the reducer's point of view — retrying the consumer
    replays the same abort — so the engine treats it as no-retry and the
    stage fails fast with the abort's message (which carries the map-stage
    error when there was one)."""


class _StreamStage:
    """Seal ledger of ONE pipelined shuffle stage: the latest generation of
    every map task's consolidated blob (``map_id -> (gen, ref_id, blob_size,
    bucket_index)``). A regenerated producer re-seals under the same map_id
    with a higher generation; reducers holding the older generation's decoded
    portion keep it (reruns are byte-identical), reducers whose fetch of the
    stale range fails refetch the newer one."""

    __slots__ = ("num_maps", "seals", "aborted")

    def __init__(self, num_maps: Optional[int]):
        self.num_maps = num_maps
        self.seals: Dict[int, Tuple[int, str, int, list]] = {}
        self.aborted: Optional[str] = None


class ShuffleStreamLedger:
    """Seal-notification plane of the pipelined shuffle (head-resident, next
    to the object table): the driver publishes ``(map_id, ref, per-bucket
    offset/size index)`` as each map task's consolidated blob seals — only
    the WINNING attempt's result reaches the driver, so a speculation loser
    never publishes — and already-dispatched reduce tasks long-poll for the
    events of their bucket, beginning ranged fetch + Arrow decode while the
    map tail is still running.

    Long-polls do not park an RPC dispatcher thread: ``poll`` returns a
    :class:`~raydp_tpu.runtime.rpc.DeferredReply` whose future completes on
    the next publish/abort/close or when the poll timeout lapses (a lazy
    sweeper thread that exits whenever no waiter is outstanding)."""

    TOMBSTONES = 1024  # closed stage keys remembered so late polls abort

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._stages: Dict[str, _StreamStage] = {}  # guarded-by: _lock
        # guarded-by: _lock
        self._closed: "collections.OrderedDict[str, bool]" = \
            collections.OrderedDict()
        self._waiters: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._sweeper: Optional[threading.Thread] = None  # guarded-by: _lock
        self._stopped = False  # guarded-by: _lock

    # -- driver side ----------------------------------------------------------
    def begin(self, stage_key: str, num_maps: int) -> None:
        with self._lock:
            st = self._stages.get(stage_key)
            if st is None:
                self._stages[stage_key] = _StreamStage(int(num_maps))
            else:
                st.num_maps = int(num_maps)

    def publish(self, stage_key: str, map_id: int, gen: int, ref_id: str,
                size: int, index: Sequence[Sequence[int]]) -> None:
        with self._lock:
            st = self._stages.get(stage_key)
            if st is None:
                # a legitimate publish always follows stream_begin, so an
                # unknown key is a late republish after the action closed
                # (possibly past the tombstone window) — drop it rather
                # than resurrect a stage no close() would ever remove
                return
            cur = st.seals.get(int(map_id))
            if cur is None or int(gen) > cur[0]:
                st.seals[int(map_id)] = (int(gen), ref_id, int(size),
                                         [tuple(e) for e in index])
            ready = self._collect_ready_locked(stage_key)
        self._complete(ready)

    def abort(self, stage_key: str, message: str) -> None:
        with self._lock:
            st = self._stages.get(stage_key)
            if st is None:
                return  # already closed (pollers abort via the tombstone
                #         / unknown-key path) — never resurrect the stage
            if st.aborted is None:
                st.aborted = str(message)
            ready = self._collect_ready_locked(stage_key)
        self._complete(ready)

    def close(self, stage_keys: Sequence[str]) -> None:
        ready: List[Tuple[Future, Dict[str, Any]]] = []
        with self._lock:
            for key in stage_keys:
                self._stages.pop(key, None)
                self._closed[key] = True
                while len(self._closed) > self.TOMBSTONES:
                    self._closed.popitem(last=False)
                ready.extend(self._collect_ready_locked(key))
        self._complete(ready)

    # -- reducer side ---------------------------------------------------------
    def poll(self, stage_key: str, bucket: int,
             have: Optional[Dict[int, int]], timeout_s: float):
        """Events newer than ``have`` (``map_id -> generation``) for one
        bucket, immediately when any exist (or the stage is aborted/closed),
        else a DeferredReply completed by the next publish or the timeout."""
        have = {int(k): int(v) for k, v in (have or {}).items()}
        with self._lock:
            resp = self._resp_locked(stage_key, int(bucket), have)
            if resp is not None or timeout_s <= 0 or self._stopped:
                return resp if resp is not None \
                    else self._empty_locked(stage_key)
            fut: Future = Future()
            self._waiters.append({
                "key": stage_key, "bucket": int(bucket), "have": have,
                "fut": fut,
                "deadline": _time_mod.monotonic() + float(timeout_s)})
            self._ensure_sweeper_locked()
            self._cond.notify_all()
        return DeferredReply(fut)

    # -- internals ------------------------------------------------------------
    def _empty_locked(self, stage_key: str) -> Dict[str, Any]:  # guarded-by: _lock
        st = self._stages.get(stage_key)
        return {"events": [], "aborted": None,
                "expected": st.num_maps if st is not None else None}

    def _resp_locked(self, stage_key: str, bucket: int,  # guarded-by: _lock
                     have: Dict[int, int]) -> Optional[Dict[str, Any]]:
        st = self._stages.get(stage_key)
        if st is None:
            reason = "stream closed" if stage_key in self._closed \
                else "unknown stream stage"
            return {"events": [], "aborted": f"{reason}: {stage_key}",
                    "expected": None}
        events = []
        for map_id, (gen, ref_id, size, index) in st.seals.items():
            if gen <= have.get(map_id, 0):
                continue
            if bucket >= len(index):
                raise ValueError(
                    f"bucket {bucket} out of range for stage {stage_key} "
                    f"(map {map_id} sealed {len(index)} buckets)")
            off, bsize = int(index[bucket][0]), int(index[bucket][1])
            events.append((map_id, gen, ref_id, size, off, bsize))
        if events or st.aborted is not None:
            return {"events": events, "aborted": st.aborted,
                    "expected": st.num_maps}
        return None

    def _collect_ready_locked(self, stage_key: str  # guarded-by: _lock
                              ) -> List[Tuple[Future, Dict[str, Any]]]:
        ready, keep = [], []
        for w in self._waiters:
            if w["key"] != stage_key:
                keep.append(w)
                continue
            resp = self._resp_locked(stage_key, w["bucket"], w["have"])
            if resp is not None:
                ready.append((w["fut"], resp))
            else:
                keep.append(w)
        self._waiters = keep
        return ready

    @staticmethod
    def _complete(ready: List[Tuple[Future, Dict[str, Any]]]) -> None:
        # futures complete OUTSIDE the ledger lock: a done-callback (the RPC
        # server's reply submit) must never run under it
        for fut, resp in ready:
            if not fut.done():
                fut.set_result(resp)

    def _ensure_sweeper_locked(self) -> None:  # guarded-by: _lock
        if self._sweeper is None or not self._sweeper.is_alive():
            self._sweeper = threading.Thread(
                target=self._sweep_loop, daemon=True,
                name="rdt-stream-ledger-sweep")
            self._sweeper.start()

    def _sweep_loop(self) -> None:
        while True:
            with self._lock:
                if not self._waiters:
                    self._sweeper = None
                    return
                now = _time_mod.monotonic()
                due = [w for w in self._waiters
                       if w["deadline"] <= now or self._stopped]
                if due:
                    self._waiters = [w for w in self._waiters
                                     if w not in due]
                    ready = [(w["fut"], self._empty_locked(w["key"]))
                             for w in due]
                else:
                    nxt = min(w["deadline"] for w in self._waiters)
                    self._cond.wait(timeout=max(0.01, min(nxt - now, 5.0)))
                    continue
            self._complete(ready)

    def shutdown(self) -> None:
        with self._lock:
            self._stopped = True
            waiters, self._waiters = self._waiters, []
            self._stages.clear()
            self._cond.notify_all()
        self._complete([(w["fut"], {"events": [], "expected": None,
                                    "aborted": "store shutting down"})
                        for w in waiters])


KIND_RAW = "raw"
KIND_PICKLE = "pickle"
KIND_ARROW = "arrow"

DRIVER_OWNER = "__driver__"

#: host id of the head's machine in the distributed data plane. Every other
#: store host is keyed by the node id of the agent machine hosting it.
HEAD_HOST = "head"

ENV_STORE_HOST_ID = "RDT_STORE_HOST_ID"
ENV_STORE_PAYLOAD_ADDR = "RDT_STORE_PAYLOAD_ADDR"
ENV_STORE_ARENA = "RDT_STORE_ARENA"


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """Stop Python's resource tracker from unlinking the segment at process exit.

    Lifetime is managed by the store server (and final sweep at session shutdown);
    3.12 has no ``track=False`` so we unregister manually.
    """
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:
        pass


def new_object_id() -> str:
    return secrets.token_hex(16)


@dataclass
class _Entry:
    segment: str
    size: int
    kind: str
    owner: str
    offset: int = -1  # >= 0: payload lives at this offset inside the arena
    host_id: str = HEAD_HOST  # machine holding the payload
    payload_addr: Optional[str] = None  # "host:port" serving cross-host fetches
    sealed: bool = True
    spilled: bool = False      # payload currently on disk, not in shm
    last_access: float = 0.0   # monotonic; LRU clock for spilling


class PayloadHost:
    """One machine's payload plane: local arena + per-object segments + frees.

    This is the per-node plasma role in the distributed data plane: the head
    runs one for its machine (inside :class:`ObjectStoreServer`), and every
    node agent on another machine runs its own (``runtime/node_agent.py``), so
    payload bytes are written and served where they live — readers on other
    machines fetch them with ONE direct RPC to the owning node, never through
    the head. Parity: per-node plasma stores + ``getBlockLocations`` routing
    (reference RayDPExecutor.scala:271-287, RayDatasetRDD.scala:48-56).
    """

    #: seconds an arena-resident payload stays mapped after its free. Readers
    #: hold *borrowed* zero-copy views (``get_buffer`` / ``get(zero_copy=True)``,
    #: e.g. the device feed's epoch-long block tables) and frees can arrive
    #: asynchronously (owner-death sweeps, executor-shrink); an immediate
    #: ``rdt_free`` would let a writer recycle bytes under a live view. The
    #: per-object-segment mode never had this hazard (unlink preserves mapped
    #: contents), so arena mode defers reclamation for a grace period instead.
    ARENA_FREE_GRACE_S = float(knobs.get("RDT_ARENA_FREE_GRACE_S"))

    def __init__(self, arena=None):
        self._arena = arena
        # rdt_free/munmap on the arena base must not interleave: a supervisor
        # or RPC thread freeing a dead owner's blocks races session shutdown.
        self._arena_lock = threading.Lock()
        self._deferred: List[Tuple] = []  # guarded-by: _arena_lock; (due, kind, payload)

    # -- arena ----------------------------------------------------------------
    def arena_info(self) -> Optional[Dict[str, Any]]:
        if self._arena is None:
            return None
        return {"segment": self._arena.segment, "size": self._arena.size}

    def arena_stats(self) -> Optional[Dict[str, int]]:
        with self._arena_lock:
            return None if self._arena is None else self._arena.stats()

    def reap(self) -> bool:
        """Free deferred allocations whose grace elapsed (writers call this
        when the arena looks full before falling back to segments)."""
        self._reap_deferred()
        return True

    # -- payload IO ------------------------------------------------------------
    def fetch(self, segment: str, offset: int, size: int) -> bytes:
        """Payload bytes for a reader on ANOTHER machine (one direct hop)."""
        return self.fetch_range(segment, offset, 0, size)

    def fetch_range(self, segment: str, base: int, start: int,
                    size: int) -> bytes:
        """A byte range of a payload hosted here: ``base`` locates the
        payload (its arena offset, or -1 for a dedicated segment — the same
        convention the object table records), ``start`` is the range offset
        WITHIN the payload. The two cannot be folded into one absolute
        offset: a positive value means "arena" to this plane, so a ranged
        read of a dedicated segment must keep them apart."""
        if base >= 0:
            with self._arena_lock:
                if self._arena is None or segment != self._arena.segment:
                    raise KeyError(f"arena segment {segment} not hosted here")
                return bytes(self._arena.view(base + start, size))
        shm = shared_memory.SharedMemory(name=segment)
        try:
            _untrack(shm)
            return bytes(shm.buf[start:start + size])
        finally:
            shm.close()

    def write(self, data: bytes, segment_name: str) -> Tuple[str, int]:
        """Write bytes locally (arena first, dedicated segment fallback);
        returns ``(segment, offset)`` with ``offset=-1`` for a segment."""
        size = len(data)
        with self._arena_lock:
            if self._arena is not None:
                offset = self._arena.alloc(size)
                if offset is not None:
                    try:
                        if size:
                            self._arena.view(offset, size)[:] = data
                    except BaseException:
                        self._arena.free(offset)
                        raise
                    return self._arena.segment, offset
        shm = shared_memory.SharedMemory(name=segment_name, create=True,
                                         size=max(size, 1))
        try:
            if size:
                shm.buf[:size] = data
        finally:
            _untrack(shm)
            shm.close()
        return segment_name, -1

    # -- release ---------------------------------------------------------------
    def release(self, items: List[Tuple[str, int]],
                defer_segments: bool = False) -> int:
        """Release payloads: ``(segment, offset)`` pairs. Arena offsets are
        deferred for the view-grace period; dedicated segments unlink now —
        unless ``defer_segments`` (the spill path uses it: a reader that
        looked the object up but has not yet attached the segment must still
        find the name for the grace period; unlink preserves only mappings
        that already exist)."""
        import time as _time
        due = _time.monotonic() + self.ARENA_FREE_GRACE_S
        n = 0
        with self._arena_lock:
            for segment, offset in items:
                if offset >= 0:
                    if self._arena is not None:
                        self._deferred.append((due, "arena", int(offset)))
                elif defer_segments:
                    self._deferred.append((due, "segment", segment))
                else:
                    _unlink_segment(segment)
                n += 1
        self._reap_deferred()
        return n

    def _reap_deferred(self, everything: bool = False) -> None:
        """Free deferred payloads whose grace period elapsed (activity-driven:
        called on frees and seals; shutdown reaps everything)."""
        import time as _time
        now = _time.monotonic()
        with self._arena_lock:
            keep = []
            for due, kind, payload in self._deferred:
                if everything or due <= now:
                    if kind == "arena":
                        if self._arena is not None:
                            self._arena.free(payload)
                    else:
                        _unlink_segment(payload)
                else:
                    keep.append((due, kind, payload))
            self._deferred = keep

    def shutdown(self) -> None:
        self._reap_deferred(everything=True)
        with self._arena_lock:
            if self._arena is not None:
                self._arena.close()
                self._arena = None


class ObjectStoreServer:
    """Metadata server for the object table. Runs inside the head process.

    All methods are called through the head's RPC server; they must stay cheap —
    object payloads never pass through here, only segment names. The head's
    machine-local payload plane (arena + segments) is an embedded
    :class:`PayloadHost`; payloads on agent machines are released/fetched
    through the ``node_release`` / ``node_fetch`` callbacks the runtime wires
    to the owning node's agent RPC.
    """

    def __init__(self, session_id: str, arena=None,
                 spill_dir: Optional[str] = None,
                 shm_budget: Optional[int] = None):
        self.session_id = session_id
        self.host = PayloadHost(arena)
        self._lock = threading.Lock()
        self._table: Dict[str, _Entry] = {}  # guarded-by: _lock
        #: head-mediated payload RPC counters — the distributed-plane tests
        #: assert these stay flat while cross-node traffic flows node→node
        self.payload_rpc_count = 0
        # per-method control-plane op counters: how many table operations the
        # session issued (a seal_batch of 100 entries counts ONE op — that is
        # the point of batching; benchmarks read these to fence the
        # metadata-plane reduction of the consolidated shuffle path)
        self._op_lock = threading.Lock()
        self._op_counts: Dict[str, int] = {}  # guarded-by: _op_lock
        # callbacks wired by RuntimeContext for payloads on agent machines
        self.node_release = None  # (host_id, [(segment, offset)]) -> None
        self.node_fetch = None    # (host_id, segment, offset, size) -> bytes
        self.node_spill = None    # (host_id, oid, segment, offset, size)
        self.node_fault_in = None  # (host_id, oid, seg_name) -> (seg, off)
        self.node_remove_spill = None  # (host_id, [oids]) -> None
        # per-node shm accounting (the head owns the table and the LRU
        # decision; the payload IO happens on the owning node)
        self._host_bytes: Dict[str, int] = {}  # guarded-by: _lock
        self._host_budgets: Dict[str, int] = {}  # guarded-by: _lock
        # eviction/spill (plasma parity): sealed head-host objects LRU-spill
        # to disk once their shm footprint exceeds the budget; lookups fault
        # them back in transparently. Disabled when spill_dir is None.
        self.spill_dir = spill_dir
        self.shm_budget = shm_budget
        self._shm_bytes = 0        # unspilled head-host payload bytes
        self._spilled_bytes = 0
        # stage-aware eviction hints (doc/etl.md "Store budgets"): the
        # engine pins the blobs of the stage it is currently consuming
        # (refcounted — concurrent stages can share inputs) and demotes
        # them to evict-first once their consumer stage completes. The
        # spill victim sort reads these as a priority band; LRU breaks
        # ties only. Pinned blobs still spill as a LAST resort — the
        # budget invariant outranks any hint.
        self._pin_counts: Dict[str, int] = {}  # guarded-by: _lock
        self._evict_first: set = set()         # guarded-by: _lock
        # AQE-derived per-host budgets (derive_budgets): when set they
        # tighten the statically configured capacity, never exceed it
        self._derived_budgets: Dict[str, int] = {}  # guarded-by: _lock
        self._spill_locks: Dict[str, threading.Lock] = {}
        self._fault_gen = 0        # fault-in segments get fresh names (the
        #                            old name may still be alive under grace)
        # pipelined-shuffle seal notifications (doc/etl.md "Pipelined
        # shuffle"): the metadata-plane extension reducers long-poll
        self._streams = ShuffleStreamLedger()

    # -- control-plane accounting ---------------------------------------------
    def _count_op(self, name: str) -> None:
        with self._op_lock:
            self._op_counts[name] = self._op_counts.get(name, 0) + 1
        # registry twin of op_counts(): metrics_report()'s store_ops_total
        # subsumes this dict (which stays as the compatible view)
        metrics.inc("store_ops_total", label=name)

    def op_counts(self) -> Dict[str, int]:
        """Per-method control-plane operation counts since start/reset. A
        batch call counts one op regardless of batch size."""
        with self._op_lock:
            return dict(self._op_counts)

    def reset_op_counts(self) -> None:
        with self._op_lock:
            self._op_counts.clear()

    # -- arena (head machine) --------------------------------------------------
    def arena_info(self) -> Optional[Dict[str, Any]]:
        return self.host.arena_info()

    def arena_stats(self) -> Optional[Dict[str, int]]:
        return self.host.arena_stats()

    def arena_reap(self) -> bool:
        return self.host.reap()

    # -- write path -----------------------------------------------------------
    def seal(self, object_id: str, segment: str, size: int, kind: str,
             owner: str, offset: int = -1, host_id: str = HEAD_HOST,
             payload_addr: Optional[str] = None) -> None:
        self._count_op("seal")
        self._seal_locked([(object_id, segment, size, kind, owner, offset,
                            host_id, payload_addr)])
        self.host.reap()
        self._maybe_spill(host_id, exclude=object_id)

    def seal_batch(self, entries: List[Sequence]) -> None:
        """Seal many objects in ONE control-plane operation; each entry is
        the positional argument tuple of :meth:`seal`. All-or-nothing: a
        duplicate id rejects the whole batch before any entry lands, so the
        caller's rollback (release the written payloads) stays simple."""
        self._count_op("seal_batch")
        entries = [tuple(e) for e in entries]
        self._seal_locked(entries)
        self.host.reap()
        by_host: Dict[str, set] = {}
        for e in entries:
            by_host.setdefault(e[6] if len(e) > 6 else HEAD_HOST,
                               set()).add(e[0])
        for host_id, ids in by_host.items():
            # exclude every id the batch just sealed on this host — same
            # immediate-re-evict guard seal() applies to its one object
            self._maybe_spill(host_id, exclude=ids)

    def _seal_locked(self, entries: List[Sequence]) -> None:
        import time as _time
        with self._lock:
            for e in entries:
                if e[0] in self._table:
                    raise KeyError(f"object {e[0]} already sealed")
            if len({e[0] for e in entries}) != len(entries):
                raise KeyError("duplicate object id in seal batch")
            now = _time.monotonic()
            for e in entries:
                (object_id, segment, size, kind, owner) = e[:5]
                offset = e[5] if len(e) > 5 else -1
                host_id = e[6] if len(e) > 6 else HEAD_HOST
                payload_addr = e[7] if len(e) > 7 else None
                self._table[object_id] = _Entry(segment, size, kind, owner,
                                                offset, host_id, payload_addr,
                                                last_access=now)
                if host_id == HEAD_HOST:
                    self._shm_bytes += size
                else:
                    self._host_bytes[host_id] = \
                        self._host_bytes.get(host_id, 0) + size

    # -- eviction/spill (one implementation; per-host backends) ---------------
    def _spill_path(self, object_id: str) -> str:
        return os.path.join(self.spill_dir, object_id)

    def register_node_budget(self, host_id: str, budget: Optional[int]) -> None:
        if budget:
            with self._lock:
                self._host_budgets[host_id] = int(budget)

    def _budget_of(self, host_id: str) -> Optional[int]:
        if host_id == HEAD_HOST:
            static = self.shm_budget if self.spill_dir is not None else None
        else:
            with self._lock:
                static = self._host_budgets.get(host_id) \
                    if self.node_spill is not None else None
        if not static:
            return None
        # an AQE-derived budget only ever TIGHTENS the configured capacity
        # (derive_budgets clamps it); absent a derivation the static
        # ENV_STORE_* number stands
        with self._lock:
            derived = self._derived_budgets.get(host_id)
        return min(int(static), derived) if derived else static

    def _shm_used(self, host_id: str) -> int:  # guarded-by: _lock
        return self._shm_bytes if host_id == HEAD_HOST \
            else self._host_bytes.get(host_id, 0)

    def _adjust_shm(self, host_id: str, delta: int) -> None:  # guarded-by: _lock
        if host_id == HEAD_HOST:
            self._shm_bytes += delta
        else:
            self._host_bytes[host_id] = \
                self._host_bytes.get(host_id, 0) + delta

    def _spill_lock(self, host_id: str) -> threading.Lock:
        """One spill/fault-in at a time PER HOST: a slow or dead node must
        not stall the head plane (or other nodes) behind its 120s RPCs."""
        with self._lock:
            lock = self._spill_locks.get(host_id)
            if lock is None:
                lock = self._spill_locks[host_id] = threading.Lock()
            return lock

    def _backend(self, host_id: str):
        """(write_spill, release_shm, fault_read, remove_spill) for a host —
        head-local file/shm IO, or the owning node's agent RPCs. Everything
        above this seam (LRU choice, survive re-check, counters) is shared."""
        if host_id == HEAD_HOST:
            def write_spill(oid, segment, offset, size):
                data = self.host.fetch(segment, offset, size)
                os.makedirs(self.spill_dir, exist_ok=True)
                tmp = self._spill_path(oid) + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, self._spill_path(oid))

            def release_shm(segment, offset):
                self.host.release([(segment, offset)], defer_segments=True)

            def fault_read(oid, seg_name):
                # the spill file is NOT deleted here: removal is directed by
                # the caller only after the table committed the new location
                with open(self._spill_path(oid), "rb") as f:
                    data = f.read()
                return self.host.write(data, seg_name)

            def remove_spill(oid):
                _remove_quiet(self._spill_path(oid))
        else:
            def write_spill(oid, segment, offset, size):
                self.node_spill(host_id, oid, segment, offset, size)

            def release_shm(segment, offset):
                self.node_release(host_id, [(segment, offset)],
                                  defer_segments=True)

            def fault_read(oid, seg_name):
                segment, offset = self.node_fault_in(host_id, oid, seg_name)
                return segment, int(offset)

            def remove_spill(oid):
                if self.node_remove_spill is not None:
                    try:
                        self.node_remove_spill(host_id, [oid])
                    except Exception:
                        pass
        return write_spill, release_shm, fault_read, remove_spill

    def _maybe_spill(self, host_id: str = HEAD_HOST,
                     exclude=None) -> None:
        """LRU-spill sealed objects on ``host_id`` until its shm use fits its
        budget. Shm bytes are released on the view-grace deferral (segments
        included), so borrowed zero-copy views and lookup-then-attach readers
        never see recycled bytes. ``exclude`` (an id or a set of ids — a
        seal batch protects ALL its entries) exempts just-sealed objects
        from being the victim of their own seal. Parity: plasma
        eviction/spill.

        Victim order is (hint band, LRU): evict-first blobs (their
        consumer stage completed) go before unhinted ones, and blobs
        pinned by a running stage go LAST — spilled only when nothing
        else can satisfy the budget, because the budget invariant
        outranks any hint (the out-of-core bench's bounded-shm
        contract). LRU breaks ties within a band."""
        budget = self._budget_of(host_id)
        if not budget:
            return
        excluded = (exclude if isinstance(exclude, (set, frozenset))
                    else {exclude} if exclude is not None else set())
        while True:
            with self._lock:
                if self._shm_used(host_id) <= budget:
                    return
                victims = sorted(
                    ((0 if oid in self._evict_first
                      else 2 if self._pin_counts.get(oid) else 1,
                      e.last_access, oid)
                     for oid, e in self._table.items()
                     if e.host_id == host_id and not e.spilled
                     and e.size > 0 and oid not in excluded))
                if not victims:
                    return
                victim = victims[0][2]
            if not self._spill_one(host_id, victim):
                return

    def _spill_one(self, host_id: str, object_id: str) -> bool:
        write_spill, release_shm, _, remove_spill = self._backend(host_id)
        survived = False
        with self._spill_lock(host_id):
            with self._lock:
                e = self._table.get(object_id)
                if e is None or e.spilled or e.host_id != host_id:
                    return False
                segment, offset, size = e.segment, e.offset, e.size
            # chaos site: checked only after the victim is validated — a
            # raced no-op spill (victim freed / already spilled) must not
            # consume the schedule (nth/times/once) while injecting
            # nothing. ``drop`` deletes the spill FILE after the commit
            # (the lost-disk model — the next fault-in surfaces the typed
            # loss into lineage recovery); delay/raise model slow/failing
            # spill IO and are applied INSIDE the write try, so an
            # injected raise fails just this spill (warning + object stays
            # in shm) instead of escaping into the seal path after the
            # table entry was committed.
            rule = faults.check("store.spill", key=object_id)
            drop_after = rule is not None and rule.action == "drop"
            try:
                if rule is not None and not drop_after:
                    faults.apply(rule, "store.spill")
                write_spill(object_id, segment, offset, size)
            except Exception as exc:
                logger.warning("spill of %s on %s failed: %s",
                               object_id, host_id, exc)
                return False
            with self._lock:
                e = self._table.get(object_id)
                if e is not None:
                    e.spilled = True
                    e.segment, e.offset = "", -1
                    self._adjust_shm(host_id, -size)
                    self._spilled_bytes += size
                    survived = True
        # backend IO OUTSIDE the table lock (for node hosts these are RPCs
        # and must not stall every seal/lookup/free behind them):
        if not survived:
            # freed while we were writing: free() already released the shm —
            # only the now-orphaned spill file needs to go (the shm must NOT
            # be released twice; an offset double-free would reclaim someone
            # else's live bytes)
            remove_spill(object_id)
            return True
        # exactly-once, only after the entry survived the write
        try:
            release_shm(segment, offset)
        except Exception as exc:
            logger.warning("post-spill release on %s failed: %s",
                           host_id, exc)
        if drop_after:
            try:
                remove_spill(object_id)
            except Exception:  # noqa: BLE001 - injection must not mask IO
                pass
        return True

    def _fault_in(self, host_id: str, object_id: str) -> None:
        """Bring a spilled payload back into shm (transparent on lookup).

        The spill file is removed only AFTER the table commits the new shm
        location: a fault-in whose result is lost (dropped RPC reply, slow
        node exceeding the call timeout) leaves the file in place, so the
        next lookup simply retries instead of losing the object forever."""
        import time as _time
        _, release_shm, fault_read, remove_spill = self._backend(host_id)
        committed = False
        with self._spill_lock(host_id):
            with self._lock:
                e = self._table.get(object_id)
                if e is None or not e.spilled:
                    return  # raced with another fault-in or a free
                size = e.size
            self._fault_gen += 1
            seg_name = (f"rdt{self.session_id[:8]}_{object_id[:20]}"
                        f"g{self._fault_gen}")
            try:
                segment, offset = fault_read(object_id, seg_name)
            except Exception as exc:
                if not (isinstance(exc, FileNotFoundError)
                        or getattr(exc, "exc_type", None)
                        == "FileNotFoundError"):
                    raise
                # the spill FILE is gone (disk loss, node wipe) — not a
                # lost RPC reply: the payload is unrecoverable here.
                # Surface the typed loss (→ lineage recovery) and drop the
                # zombie table entry so later readers miss fast instead of
                # re-probing a file that will never return
                with self._lock:
                    e = self._table.get(object_id)
                    if e is not None and e.spilled:
                        del self._table[object_id]
                        self._spilled_bytes -= e.size
                raise ObjectLostError(
                    object_id, f"spill file lost on {host_id}: {exc}") \
                    from exc
            with self._lock:
                e = self._table.get(object_id)
                if e is None:  # freed mid-fault-in: drop the fresh shm
                    try:
                        release_shm(segment, offset)
                    except Exception:
                        pass
                    return  # free() already removed the spill file
                e.segment, e.offset = segment, offset
                e.spilled = False
                e.last_access = _time.monotonic()
                self._adjust_shm(host_id, size)
                self._spilled_bytes -= size
                committed = True
        if committed:
            metrics.inc("store_fault_in_total")
            metrics.record_event("store_fault_in", object_id=object_id,
                                 host=host_id)
            remove_spill(object_id)
        self._maybe_spill(host_id, exclude=object_id)

    # -- head-mediated payload path (clients with NO shared memory at all) -----
    def fetch_payload(self, object_id: str) -> Tuple[bytes, str]:
        """Payload bytes + kind through the head — the slow compatibility path
        for shm-less clients. Machine-local readers attach segments directly;
        cross-machine readers go straight to the owning node's PayloadHost."""
        self._count_op("fetch_payload")
        segment, size, kind, offset, host_id, _ = self._lookup_one(object_id)
        self.payload_rpc_count += 1
        if host_id != HEAD_HOST:
            if self.node_fetch is None:
                raise KeyError(f"object {object_id} lives on {host_id}; "
                               "no node fetch route")
            return self.node_fetch(host_id, segment, offset, size), kind
        return self.host.fetch(segment, offset, size), kind

    def store_payload(self, object_id: str, data: bytes, kind: str,
                      owner: str) -> int:
        """Write + seal on behalf of a shm-less client; returns the size."""
        self._count_op("store_payload")
        self.payload_rpc_count += 1
        seg_name = f"rdt{self.session_id[:8]}_{object_id}"
        segment, offset = self.host.write(data, seg_name)
        try:
            self.seal(object_id, segment, len(data), kind, owner, offset)
        except BaseException:
            self.host.release([(segment, offset)])
            raise
        return len(data)

    # -- read path ------------------------------------------------------------
    def lookup(self, object_id: str
               ) -> Tuple[str, int, str, int, str, Optional[str]]:
        self._count_op("lookup")
        return self._lookup_one(object_id)

    def lookup_batch(self, object_ids: List[str]
                     ) -> Dict[str, Tuple[str, int, str, int, str,
                                          Optional[str]]]:
        """Resolve many objects in ONE control-plane operation. Missing ids
        are simply absent from the result (the caller decides whether a miss
        is a lost object); present-but-spilled entries fault in exactly like
        :meth:`lookup`."""
        self._count_op("lookup_batch")
        out = {}
        for oid in object_ids:
            try:
                out[oid] = self._lookup_one(oid)
            except KeyError:
                pass
        return out

    def _lookup_one(self, object_id: str
                    ) -> Tuple[str, int, str, int, str, Optional[str]]:
        import time as _time
        # a concurrent seal can re-evict the object between our fault-in and
        # re-read (it is the LRU victim when it is the only candidate): retry
        # a few rounds rather than failing a live ref
        for _ in range(4):
            with self._lock:
                e = self._table.get(object_id)
                if e is None:
                    raise KeyError(f"object {object_id} not found")
                e.last_access = _time.monotonic()
                if not e.spilled:
                    return (e.segment, e.size, e.kind, e.offset, e.host_id,
                            e.payload_addr)
                host_id = e.host_id
            self._fault_in(host_id, object_id)
        raise RuntimeError(
            f"object {object_id} is thrashing between shm and spill; "
            "raise raydp.tpu.object_store.shm_budget")

    def contains(self, object_id: str) -> bool:
        self._count_op("contains")
        with self._lock:
            return object_id in self._table

    def locations(self, object_ids: List[str]) -> Dict[str, str]:
        """``object_id -> host_id`` for the ids present — the engine's
        locality source (parity: ``getBlockLocations`` / preferred locations,
        RayDPExecutor.scala:271-287, RayDatasetRDD.scala:48-56)."""
        self._count_op("locations")
        with self._lock:
            return {oid: self._table[oid].host_id for oid in object_ids
                    if oid in self._table}

    def residency(self, object_ids: List[str]
                  ) -> Dict[str, Tuple[str, str]]:
        """``object_id -> (host_id, tier)`` for the ids present; tier is
        ``"shm"`` (payload resident in shared memory on that host) or
        ``"spilled"`` (on that host's disk — a read pays a fault-in
        first). The tier-blind view is :meth:`locations`; the engine's
        data-gravity weighting reads this one, so a host holding only a
        spilled copy scores between in-memory-local and remote
        (doc/etl.md "Data-gravity scheduling")."""
        self._count_op("residency")
        with self._lock:
            return {oid: (self._table[oid].host_id,
                          "spilled" if self._table[oid].spilled else "shm")
                    for oid in object_ids if oid in self._table}

    def eviction_hints(self, pin: Optional[List[str]] = None,
                       unpin: Optional[List[str]] = None,
                       evict_first: Optional[List[str]] = None
                       ) -> Dict[str, int]:
        """Stage-aware eviction hints from the engine's stage ledger:
        ``pin`` marks blobs a dispatching stage is about to consume
        (refcounted — concurrent stages can share inputs), ``unpin``
        releases one pin and, at refcount zero, demotes the blob to
        evict-first (its consumer stage completed), ``evict_first``
        demotes explicitly. Advisory only: :meth:`_maybe_spill` reads
        the bands, the budget invariant always wins. Returns the live
        band sizes."""
        self._count_op("eviction_hints")
        with self._lock:
            for oid in pin or ():
                self._pin_counts[oid] = self._pin_counts.get(oid, 0) + 1
                self._evict_first.discard(oid)
            for oid in unpin or ():
                n = self._pin_counts.get(oid)
                if n is None:
                    continue
                if n <= 1:
                    del self._pin_counts[oid]
                    self._evict_first.add(oid)
                else:
                    self._pin_counts[oid] = n - 1
            for oid in evict_first or ():
                if not self._pin_counts.get(oid):
                    self._evict_first.add(oid)
            return {"pinned": len(self._pin_counts),
                    "evict_first": len(self._evict_first)}

    def derive_budgets(self, measured_bytes: int) -> Dict[str, int]:
        """Re-derive per-host shm budgets from the AQE plane's measured
        per-stage bytes: derived = min(static capacity, measured x
        RDT_STORE_BUDGET_HEADROOM), floored at 1 MiB. Derived budgets
        only ever TIGHTEN the statically configured ``ENV_STORE_*``
        capacity — when the measured working set is smaller, cold bytes
        spill ahead of demand; a workload bigger than capacity keeps the
        static number. Hosts without spill plumbing are untouched.

        The ``store.budget`` chaos site fires here (key: the measured
        byte count); an injected failure degrades LOUDLY to the static
        budgets (derived state cleared) instead of erroring."""
        self._count_op("derive_budgets")
        measured = max(0, int(measured_bytes))
        rule = faults.check("store.budget", key=str(measured))
        if rule is not None:
            try:
                faults.apply(rule, "store.budget")
            except Exception as exc:
                logger.warning("store budget derivation failed (injected); "
                               "keeping static budgets: %s", exc)
                with self._lock:
                    self._derived_budgets.clear()
                metrics.record_event("store_budget",
                                     measured_bytes=measured, degraded=True)
                return {}
        headroom = max(0.0, float(knobs.get("RDT_STORE_BUDGET_HEADROOM")))
        target = max(1 << 20, int(measured * headroom))
        derived: Dict[str, int] = {}
        with self._lock:
            if self.shm_budget and self.spill_dir is not None:
                derived[HEAD_HOST] = min(int(self.shm_budget), target)
            if self.node_spill is not None:
                for host_id, cap in self._host_budgets.items():
                    derived[host_id] = min(int(cap), target)
            self._derived_budgets = dict(derived)
        metrics.record_event("store_budget", measured_bytes=measured,
                             headroom=headroom, hosts=len(derived),
                             budget=(target if derived else 0))
        # a tightened budget spills cold bytes ahead of demand, off the
        # read/write hot paths
        for host_id in derived:
            self._maybe_spill(host_id)
        return derived

    # -- pipelined-shuffle seal notifications ----------------------------------
    def stream_begin(self, stage_key: str, num_maps: int) -> None:
        """Open a seal stream for one shuffle stage (driver, before any
        reduce task dispatches — a poll on a never-begun stage aborts)."""
        self._count_op("stream_begin")
        self._streams.begin(stage_key, num_maps)

    def stream_publish(self, stage_key: str, map_id: int, gen: int,
                       ref_id: str, size: int,
                       index: Sequence[Sequence[int]]) -> None:
        """Seal notification: map ``map_id``'s consolidated blob (generation
        ``gen`` — a lineage-regenerated producer re-seals with gen+1) with
        its per-bucket (offset, size, rows) index."""
        self._count_op("stream_publish")
        self._streams.publish(stage_key, map_id, gen, ref_id, size, index)

    def stream_poll(self, stage_key: str, bucket: int,
                    have: Optional[Dict[int, int]] = None,
                    timeout_s: float = 10.0):
        """Long-poll one bucket's seal events newer than ``have``; may return
        a DeferredReply (completed on publish/abort/close or timeout)."""
        self._count_op("stream_poll")
        return self._streams.poll(stage_key, bucket, have, timeout_s)

    def stream_abort(self, stage_key: str, message: str) -> None:
        self._count_op("stream_abort")
        self._streams.abort(stage_key, message)

    def stream_close(self, stage_keys: List[str]) -> None:
        """Action end: drop the stage ledgers; drain-abandoned reducers still
        polling get an abort instead of waiting forever."""
        self._count_op("stream_close")
        self._streams.close(stage_keys)

    def fetch_ranges(self, items: List[Sequence]) -> List[bytes]:
        """Byte ranges of payloads hosted on the HEAD machine, one RPC for
        many ranges: each item is ``(segment, base, start, size)`` — the
        payload's table offset (arena offset or -1 for a dedicated segment)
        plus the range offset within it. This is the head acting as its
        machine's payload host (the node-agent twin is
        ``store_fetch_ranges``), serving consolidated shuffle blobs to
        readers on other machines without one round-trip per range."""
        self._count_op("fetch_ranges")
        return [self.host.fetch_range(seg, int(base), int(start), int(size))
                for seg, base, start, size in items]

    # -- lifetime: ownership-based (owner death sweeps; explicit free releases).
    # A refcount protocol is deliberately absent — every object has exactly one
    # owner and lineage makes re-creation cheap, so ownership is the whole story.
    def free(self, object_ids: List[str]) -> int:
        """Explicitly delete objects regardless of owner (release path,
        parity with ``release_spark_recoverable``, dataset.py:224-237)."""
        self._count_op("free")
        freed = []
        with self._lock:
            for oid in object_ids:
                # eviction-hint state dies with the blob (a stale hint for
                # a reused id would misprioritize the newcomer)
                self._pin_counts.pop(oid, None)
                self._evict_first.discard(oid)
                e = self._table.pop(oid, None)
                if e is not None:
                    freed.append((oid, e))
        self._release_payloads(freed)
        return len(freed)

    def _release_payloads(self, entries: List[Tuple[str, _Entry]]) -> None:
        local = []
        for oid, e in entries:
            if e.host_id != HEAD_HOST:
                continue
            if e.spilled:
                _remove_quiet(self._spill_path(oid))
                with self._lock:
                    self._spilled_bytes -= e.size
            else:
                local.append((e.segment, e.offset))
                with self._lock:
                    self._shm_bytes -= e.size
        if local:
            self.host.release(local)
        by_node: Dict[str, List[Tuple[str, int]]] = {}
        spill_removals: Dict[str, List[str]] = {}
        for oid, e in entries:
            if e.host_id == HEAD_HOST:
                continue
            if e.spilled:
                with self._lock:
                    self._spilled_bytes -= e.size
                spill_removals.setdefault(e.host_id, []).append(oid)
            else:
                with self._lock:
                    self._host_bytes[e.host_id] = \
                        self._host_bytes.get(e.host_id, 0) - e.size
                by_node.setdefault(e.host_id, []).append((e.segment, e.offset))
        for host_id, oids in spill_removals.items():
            # one batched RPC per host, like the shm-release path below
            if self.node_remove_spill is None:
                continue
            try:
                self.node_remove_spill(host_id, oids)
            except Exception:
                pass
        for host_id, items in by_node.items():
            if self.node_release is None:
                continue
            try:
                self.node_release(host_id, items)
            except Exception as exc:  # node may be dead; lineage re-creates
                logger.warning("release on node %s failed: %s", host_id, exc)

    def transfer_ownership(self, object_ids: List[str], new_owner: str) -> int:
        with self._lock:
            n = 0
            for oid in object_ids:
                e = self._table.get(oid)
                if e is not None:
                    e.owner = new_owner
                    n += 1
            return n

    def free_owned_by(self, owner: str) -> int:
        """Called when an owner (actor) dies or is stopped with cleanup."""
        freed = []
        with self._lock:
            for oid in [o for o, e in self._table.items() if e.owner == owner]:
                self._pin_counts.pop(oid, None)
                self._evict_first.discard(oid)
                freed.append((oid, self._table.pop(oid)))
        self._release_payloads(freed)
        return len(freed)

    def purge_host(self, host_id: str) -> int:
        """Node death: its payloads are gone — drop their table entries so
        readers fail fast into lineage recovery instead of timing out."""
        dropped = 0
        with self._lock:
            for oid in [o for o, e in self._table.items()
                        if e.host_id == host_id]:
                if self._table[oid].spilled:
                    self._spilled_bytes -= self._table[oid].size
                self._pin_counts.pop(oid, None)
                self._evict_first.discard(oid)
                del self._table[oid]
                dropped += 1
            self._host_bytes.pop(host_id, None)
            self._host_budgets.pop(host_id, None)
        if dropped:
            logger.warning("purged %d objects hosted on dead node %s",
                           dropped, host_id)
        return dropped

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            budgets: Dict[str, int] = dict(self._host_budgets)
            if self.shm_budget and self.spill_dir is not None:
                budgets[HEAD_HOST] = int(self.shm_budget)
            host_spilled: Dict[str, int] = {}
            for e in self._table.values():
                if e.spilled:
                    host_spilled[e.host_id] = \
                        host_spilled.get(e.host_id, 0) + e.size
            return {
                "num_objects": len(self._table),
                "total_bytes": sum(e.size for e in self._table.values()),
                "owners": sorted({e.owner for e in self._table.values()}),
                "hosts": sorted({e.host_id for e in self._table.values()}),
                "shm_bytes": self._shm_bytes,
                "spilled_bytes": self._spilled_bytes,
                "spilled_objects": sum(1 for e in self._table.values()
                                       if e.spilled),
                # per-host shm footprint + budgets: what the engine's
                # memory backpressure (doc/etl.md "Fair sharing and
                # admission") reads its watermark fractions from
                "host_shm": {HEAD_HOST: self._shm_bytes,
                             **dict(self._host_bytes)},
                "host_budgets": budgets,
                # residency-tier + policy-plane visibility (data-gravity
                # scheduling / stage-aware eviction): per-host spilled
                # bytes, live hint-band sizes, and any AQE-derived
                # budgets currently tightening the static capacity
                "host_spilled": host_spilled,
                "pinned_objects": len(self._pin_counts),
                "evict_first_objects": len(self._evict_first),
                "derived_budgets": dict(self._derived_budgets),
            }

    def owned_by(self, owner: str) -> List[str]:
        with self._lock:
            return [o for o, e in self._table.items() if e.owner == owner]

    def shutdown(self) -> None:
        self._streams.shutdown()
        with self._lock:
            entries = list(self._table.items())
            self._table.clear()
        # node-hosted payloads: route their release to the owning agents
        # BEFORE the runtime tears the agents down (dedicated /dev/shm
        # segments on a node would otherwise outlive the session)
        self._release_payloads([(oid, e) for oid, e in entries
                                if e.host_id != HEAD_HOST])
        for oid, e in entries:
            if e.host_id != HEAD_HOST:
                continue
            if e.spilled:
                _remove_quiet(self._spill_path(oid))
            elif e.offset < 0:
                _unlink_segment(e.segment)
        if self.spill_dir is not None:
            import shutil
            shutil.rmtree(self.spill_dir, ignore_errors=True)
        self.host.shutdown()


def _remove_quiet(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass


def _unlink_segment(segment: str) -> None:
    try:
        shm = shared_memory.SharedMemory(name=segment)
        _untrack(shm)
        shm.close()
        shm.unlink()
    except FileNotFoundError:
        pass
    except Exception as e:  # pragma: no cover
        logger.warning("failed to unlink segment %s: %s", segment, e)


@dataclass
class ObjectRef:
    """A handle to a sealed object. Picklable; resolvable in any session process.

    Parity: Ray ``ObjectRef`` + owner address as rehydrated by the reference's
    ``RayDPUtils.readBinary`` (RayDPUtils.java:45-53). ``get()`` resolves through
    the process-local :class:`ObjectStoreClient`.
    """

    id: str
    size: int = 0
    kind: str = KIND_PICKLE

    def get(self) -> Any:
        return get_client().get(self)

    def __hash__(self):
        return hash(self.id)


#: RemoteError.exc_type names meaning "the blob is unreachable where the
#: table said it was" — translated to :class:`ObjectLostError` so the reader
#: falls into lineage recovery instead of burning its retry budget. One
#: constant, not per-site tuples: the five hand-copied copies these replace
#: are exactly the drift rdtlint's ``exc-contract`` rule now guards.
_REMOTE_LOST_EXC_TYPES = ("KeyError", "ObjectLostError", "FileNotFoundError")

#: the subset meaning "possibly just a stale location" (spill/fault-in moved
#: the payload between lookup and read): worth ONE fresh-lookup retry before
#: escalating to a typed loss. KeyError joins FileNotFoundError here because
#: a peer arena that re-homed a segment reports the miss as KeyError.
_REMOTE_STALE_EXC_TYPES = ("FileNotFoundError", "KeyError")


class ObjectStoreClient:
    """Per-process client: creates/attaches segments, talks to the table server.

    ``server`` is any object exposing the ObjectStoreServer methods — in the head
    process it is the server itself; in actor processes it is an RPC proxy.
    """

    def __init__(self, server, session_id: str, default_owner: str = DRIVER_OWNER,
                 remote: Optional[bool] = None, host_id: Optional[str] = None,
                 payload_addr: Optional[str] = None):
        self._server = server
        self.session_id = session_id
        self.default_owner = default_owner
        self._attached: Dict[str, shared_memory.SharedMemory] = {}
        #: object id → the dedicated segment this process attached for it, so
        #: free()/loss can evict the handle (fault-in segments carry a
        #: generation suffix — deriving the name from the id alone misses
        #: them, which was the handle/fd leak this map fixes)
        self._seg_of: Dict[str, str] = {}
        # client-side lookup memo for sealed entries. Only entries whose
        # payload CANNOT silently move under a reader are memoized: dedicated
        # segments are written once, and any relocation (spill/fault-in) or
        # free changes/unlinks the NAME, so a stale hit surfaces as
        # FileNotFoundError and takes the existing one-fresh-lookup recovery.
        # Arena-resident entries are deliberately not memoized — the arena
        # segment name never changes, so a recycled offset would be read
        # silently.
        self._lookup_memo: Dict[str, Tuple] = {}
        self._MEMO_CAP = 4096
        #: handles whose close() failed because a borrowed view still pins
        #: the mapping; kept strongly referenced (GC-time close would just
        #: raise the same BufferError) and re-tried on later evictions
        self._retired: List[shared_memory.SharedMemory] = []
        # control-plane instrumentation: table-server calls and payload-fetch
        # RPCs issued by THIS process (executors report per-task deltas into
        # the engine's shuffle ledger). Seal-stream polls are counted apart —
        # a long-poll is a wait, not a table op, and folding it into
        # meta_rpcs would make the consolidation comparisons meaningless.
        self.meta_rpc_count = 0
        self.fetch_rpc_count = 0
        self.stream_poll_count = 0
        self._lock = threading.Lock()
        self._arena = None          # native write handle, lazily probed
        self._arena_probed = False
        # distributed data plane: which machine this process is on, and the
        # address of that machine's payload server (node agent RPC; None =
        # the head). Writes land in the machine-local arena/segments; reads
        # of objects on OTHER machines go directly to the owning node.
        self.host_id = (host_id if host_id is not None
                        else str(knobs.get(ENV_STORE_HOST_ID)))
        self.payload_addr = (payload_addr if payload_addr is not None
                             else knobs.get(ENV_STORE_PAYLOAD_ADDR))
        self._peers: Dict[str, Any] = {}  # payload_addr -> RpcClient
        # remote mode (explicit constructor opt-in): this process has no
        # usable shared memory at all; every payload read and write is
        # head-mediated — the slow compatibility path for external clients
        self.remote = bool(remote)

    # -- segment naming: session-scoped so shutdown can sweep leftovers -------
    def _segment_name(self, object_id: str) -> str:
        return f"rdt{self.session_id[:8]}_{object_id}"

    def _write_arena(self):
        """The machine-local arena handle for allocations, or None (fallback).

        Head-machine processes attach the head's arena; processes on an
        isolated node attach the node's own arena (segment name handed down
        via ``RDT_STORE_ARENA`` by the node agent that spawned them)."""
        if self._arena_probed:
            return self._arena
        with self._lock:
            if self._arena_probed:
                return self._arena
            try:
                if self.host_id != HEAD_HOST:
                    segment = knobs.get(ENV_STORE_ARENA)
                    if segment:
                        from raydp_tpu.native.arena import Arena
                        self._arena = Arena.attach(segment)
                else:
                    info = self._server.arena_info()
                    if info is not None:
                        from raydp_tpu.native.arena import Arena
                        self._arena = Arena.attach(info["segment"])
            except Exception as e:
                logger.warning("arena attach failed (%s); using per-object "
                               "segments in this process", e)
                self._arena = None
            self._arena_probed = True
        return self._arena

    def _peer(self, addr: str):
        """RPC client to another machine's payload server (node agent).
        Connects OUTSIDE the client-wide lock (a dead node's connect timeout
        must not stall unrelated same-host reads/writes in this process)."""
        with self._lock:
            client = self._peers.get(addr)
        if client is not None and not client._closed:  # noqa: SLF001
            return client
        from raydp_tpu.runtime.rpc import RpcClient
        host, port = addr.rsplit(":", 1)
        fresh = RpcClient((host, int(port)), connect_timeout=5.0)
        with self._lock:
            cur = self._peers.get(addr)
            if cur is not None and not cur._closed:  # noqa: SLF001
                fresh.close()
                return cur
            self._peers[addr] = fresh
            return fresh

    def _local_reap(self) -> None:
        """Ask this machine's payload host to reap expired deferred frees."""
        if self.host_id == HEAD_HOST:
            self._server.arena_reap()
        elif self.payload_addr:
            self._peer(self.payload_addr).call("store_reap", timeout=30.0)

    # -- write ----------------------------------------------------------------
    def _write_local(self, object_id: str, data) -> Tuple[str, int]:
        """Write payload bytes into this machine's plane (arena first with a
        reap-retry, dedicated segment fallback); returns ``(segment, offset)``
        with ``offset=-1`` for a dedicated segment. No metadata RPC happens
        here — the caller seals (individually or batched)."""
        size = len(data)
        arena = self._write_arena()
        if arena is not None:
            offset = arena.alloc(size)
            if offset is None:
                # expired deferred frees may be holding the space: reap on
                # this machine's payload host and retry once before the slow
                # per-segment path
                try:
                    self._local_reap()
                    offset = arena.alloc(size)
                except Exception:
                    offset = None
            if offset is not None:
                try:
                    if size:
                        view = arena.view(offset, size)
                        if isinstance(data, memoryview):
                            view[:] = data.cast("B")
                        else:
                            view[:] = data
                except BaseException:
                    # unsealed allocation would leak until session end
                    try:
                        arena.free(offset)
                    except Exception:
                        pass
                    raise
                return arena.segment, offset
            # arena full: fall through to a dedicated segment
        seg_name = self._segment_name(object_id)
        if size == 0:
            # shm segments cannot be zero-sized; keep 1 byte and record size=0
            shm = shared_memory.SharedMemory(name=seg_name, create=True, size=1)
        else:
            shm = shared_memory.SharedMemory(name=seg_name, create=True, size=size)
            if isinstance(data, memoryview):
                shm.buf[:size] = data.cast("B")
            else:
                shm.buf[:size] = data
        _untrack(shm)
        shm.close()
        return seg_name, -1

    def _release_local(self, items: List[Tuple[str, int]]) -> None:
        """Roll back local payload writes that never got sealed."""
        arena = self._write_arena()
        for segment, offset in items:
            try:
                if offset >= 0:
                    if arena is not None:
                        arena.free(offset)
                else:
                    _unlink_segment(segment)
            except Exception:
                pass

    def put_raw(self, data, kind: str = KIND_RAW, owner: Optional[str] = None) -> ObjectRef:
        object_id = new_object_id()
        size = len(data)
        if self.remote:
            payload = bytes(data.cast("B")) if isinstance(data, memoryview) \
                else bytes(data)
            self.meta_rpc_count += 1
            self._server.store_payload(object_id, payload, kind,
                                       owner or self.default_owner)
            return ObjectRef(id=object_id, size=size, kind=kind)
        segment, offset = self._write_local(object_id, data)
        try:
            self.meta_rpc_count += 1
            self._server.seal(object_id, segment, size, kind,
                              owner or self.default_owner, offset,
                              self.host_id, self.payload_addr)
        except BaseException:
            self._release_local([(segment, offset)])
            raise
        return ObjectRef(id=object_id, size=size, kind=kind)

    def put_raw_many(self, items: Sequence[Tuple[Any, str]],
                     owner: Optional[str] = None) -> List[ObjectRef]:
        """Write many payloads locally and seal them with ONE ``seal_batch``
        RPC — the batched half of the metadata plane (a map task's B shuffle
        buckets, or createDataFrame's N chunks, used to cost one head
        round-trip each). ``items`` are ``(data, kind)`` pairs; order is
        preserved. All-or-nothing on the seal: a rejected batch releases
        every payload written here."""
        if self.remote:
            return [self.put_raw(d, kind=k, owner=owner) for d, k in items]
        own = owner or self.default_owner
        refs: List[ObjectRef] = []
        specs: List[Tuple] = []
        written: List[Tuple[str, int]] = []
        try:
            for data, kind in items:
                object_id = new_object_id()
                size = len(data)
                segment, offset = self._write_local(object_id, data)
                written.append((segment, offset))
                specs.append((object_id, segment, size, kind, own, offset,
                              self.host_id, self.payload_addr))
                refs.append(ObjectRef(id=object_id, size=size, kind=kind))
            if specs:
                self.meta_rpc_count += 1
                self._server.seal_batch(specs)
        except BaseException:
            self._release_local(written)
            raise
        return refs

    def put(self, obj: Any, owner: Optional[str] = None) -> ObjectRef:
        if isinstance(obj, pa.Table):
            return self.put_arrow(obj, owner=owner)
        return self.put_raw(cloudpickle.dumps(obj), kind=KIND_PICKLE, owner=owner)

    def put_arrow(self, table: pa.Table, owner: Optional[str] = None) -> ObjectRef:
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        buf = sink.getvalue()
        return self.put_raw(memoryview(buf), kind=KIND_ARROW, owner=owner)

    def put_arrow_many(self, tables: Sequence[pa.Table],
                       owner: Optional[str] = None) -> List[ObjectRef]:
        """Serialize and store many tables, sealed with one batched RPC."""
        items = []
        for table in tables:
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, table.schema) as writer:
                writer.write_table(table)
            items.append((memoryview(sink.getvalue()), KIND_ARROW))
        return self.put_raw_many(items, owner=owner)

    # -- read -----------------------------------------------------------------
    def _memoize(self, object_id: str, entry: Tuple) -> None:
        segment, size, kind, offset, host_id, payload_addr = entry
        if offset >= 0:
            return  # arena-resident: a recycled offset would be read silently
        with self._lock:
            if len(self._lookup_memo) >= self._MEMO_CAP:
                self._lookup_memo.pop(next(iter(self._lookup_memo)))
            self._lookup_memo[object_id] = entry

    def _evict(self, object_id: str) -> None:
        """Drop everything this process cached about an object: the lookup
        memo entry AND the attached dedicated-segment handle (the arena
        attachment is shared by every arena object and stays). Called on
        free, on a lost object, and before a fresh-lookup retry — a stale
        handle would otherwise hold the mapping (and an open fd) for the
        life of the process."""
        with self._lock:
            self._lookup_memo.pop(object_id, None)
            seg = self._seg_of.pop(object_id, None)
            shm = self._attached.pop(seg, None) if seg is not None else None
        if shm is not None:
            self._close_handle(shm)
        self._sweep_retired()

    def _close_handle(self, shm: shared_memory.SharedMemory) -> None:
        try:
            shm.close()
        except Exception:
            # a borrowed view still pins the mapping: keep a strong ref and
            # retry later (a GC-time __del__ would raise the same
            # BufferError, just noisily)
            with self._lock:
                self._retired.append(shm)

    def _sweep_retired(self) -> None:
        with self._lock:
            retired, self._retired = self._retired, []
        for shm in retired:
            self._close_handle(shm)

    def _lookup_entry(self, object_id: str, fresh: bool = False) -> Tuple:
        if not fresh:
            with self._lock:
                hit = self._lookup_memo.get(object_id)
            if hit is not None:
                return hit
        elif not self.remote:
            self._evict(object_id)
        self.meta_rpc_count += 1
        entry = tuple(self._server.lookup(object_id))
        self._memoize(object_id, entry)
        return entry

    def lookup_many(self, object_ids: Sequence[str],
                    fresh: bool = False) -> Dict[str, Tuple]:
        """Resolve many objects with at most ONE ``lookup_batch`` RPC (memo
        hits cost nothing). Missing ids are absent from the result."""
        out: Dict[str, Tuple] = {}
        todo: List[str] = []
        for oid in dict.fromkeys(object_ids):
            hit = None
            if not fresh:
                with self._lock:
                    hit = self._lookup_memo.get(oid)
            elif not self.remote:
                self._evict(oid)
            if hit is not None:
                out[oid] = hit
            else:
                todo.append(oid)
        if todo:
            self.meta_rpc_count += 1
            for oid, entry in self._server.lookup_batch(todo).items():
                entry = tuple(entry)
                self._memoize(oid, entry)
                out[oid] = entry
        return out
    def _attach(self, object_id: str) -> Tuple[memoryview, str]:
        rule = faults.check("store.get", key=object_id)
        if rule is not None:
            if rule.action == "drop":
                # genuinely remove the blob (the store-host-died model), so
                # every later reader misses too — recovery must regenerate,
                # not merely retry
                try:
                    self._server.free([object_id])
                except Exception:
                    pass
                raise ObjectLostError(object_id, "fault-injected drop")
            faults.apply(rule, "store.get")
        try:
            try:
                return self._attach_once(object_id)
            except FileNotFoundError:
                # the payload moved (spill eviction recycled the segment
                # between our lookup and attach): one fresh lookup resolves
                # the new home (and evicts the stale memo entry + handle)
                return self._attach_once(object_id, fresh=True)
            except Exception as e:
                # the same lookup/attach race through an RPC proxy: the
                # server's FileNotFoundError arrives as a RemoteError, so it
                # gets the same single fresh-lookup retry — a still-alive
                # blob must not be escalated to "lost" (which bypasses task
                # retry and re-executes its producer)
                if getattr(e, "exc_type", None) == "FileNotFoundError":
                    return self._attach_once(object_id, fresh=True)
                raise
        except ObjectLostError:
            self._evict(object_id)
            raise
        except KeyError as e:
            # table lookup miss (head in-process) — the blob is gone
            self._evict(object_id)
            raise ObjectLostError(object_id, "not in store table") from e
        except FileNotFoundError as e:
            self._evict(object_id)
            raise ObjectLostError(object_id, f"segment vanished: {e}") from e
        except Exception as e:
            # lookup/fetch through an RPC proxy surfaces the server's
            # KeyError (table miss) or FileNotFoundError (segment vanished on
            # the payload host) as a RemoteError; duck-type on exc_type to
            # avoid importing rpc
            if getattr(e, "exc_type", None) in _REMOTE_LOST_EXC_TYPES:
                self._evict(object_id)
                raise ObjectLostError(object_id, "blob unreachable: "
                                      f"{getattr(e, 'message', e)}") from e
            raise

    def _attach_once(self, object_id: str,
                     fresh: bool = False) -> Tuple[memoryview, str]:
        if self.remote:
            self.fetch_rpc_count += 1
            data, kind = self._server.fetch_payload(object_id)
            return memoryview(data), kind
        segment, size, kind, offset, host_id, payload_addr = \
            self._lookup_entry(object_id, fresh=fresh)
        if host_id != self.host_id:
            # payload lives on another machine: ONE direct hop to the owning
            # node's payload server (never through the head — parity with
            # plasma's node-to-node object transfer)
            if payload_addr:
                import concurrent.futures as _cf
                try:
                    # bounded: a wedged-but-connected owner must fail the
                    # read into task retry / lineage recovery, not hang it
                    self.fetch_rpc_count += 1
                    data = self._peer(payload_addr).call(
                        "store_fetch", segment, offset, size, timeout=60.0)
                except (OSError, _cf.TimeoutError, TimeoutError) as e:
                    # the store host died/wedged with the table entry still
                    # present (purge_host lags the death): this IS the
                    # lost-blob case — surface the typed signal so lineage
                    # recovery regenerates instead of the consumer burning
                    # its retry budget against a dead host. ConnectionLost
                    # subclasses RpcError, not OSError — duck-type it.
                    raise ObjectLostError(
                        object_id,
                        f"payload host {payload_addr} unreachable: {e}") \
                        from e
                except Exception as e:
                    if type(e).__name__ == "ConnectionLost":
                        raise ObjectLostError(
                            object_id,
                            f"payload host {payload_addr} unreachable: {e}") \
                            from e
                    raise
            else:  # owner is the head machine; the table server serves it
                self.fetch_rpc_count += 1
                data, kind = self._server.fetch_payload(object_id)
            return memoryview(data), kind
        view = self._local_view(object_id, segment, offset, size)
        return view, kind

    def _local_view(self, object_id: str, segment: str, offset: int,
                    size: int) -> memoryview:
        """Zero-copy view of a same-machine payload, attaching (and caching)
        the segment handle. Dedicated segments are recorded per object id so
        free/loss can evict the handle."""
        with self._lock:
            shm = self._attached.get(segment)
            if shm is None:
                shm = shared_memory.SharedMemory(name=segment)
                _untrack(shm)
                self._attached[segment] = shm
            if offset < 0:
                self._seg_of[object_id] = segment
        if offset >= 0:
            return shm.buf[offset:offset + size]
        return shm.buf[:size]

    def get_buffer(self, ref: ObjectRef) -> memoryview:
        """Borrowed zero-copy view; valid only until the object is freed."""
        view, _ = self._attach(ref.id)
        return view

    def get(self, ref: ObjectRef, zero_copy: bool = False) -> Any:
        """Resolve an object. Arrow payloads copy their IPC stream out of the
        store by default so the result outlives ``free``; hot paths that
        consume the table immediately (e.g. the device feed, which copies to
        HBM anyway) pass ``zero_copy=True`` to decode in place."""
        view, kind = self._attach(ref.id)
        if kind == KIND_ARROW:
            buf = pa.py_buffer(view) if zero_copy else pa.py_buffer(bytes(view))
            return pa.ipc.open_stream(buf).read_all()
        if kind == KIND_PICKLE:
            return cloudpickle.loads(bytes(view))
        return bytes(view)

    def get_many(self, refs: List[ObjectRef], zero_copy: bool = False) -> List[Any]:
        return [self.get(r, zero_copy=zero_copy) for r in refs]

    # -- ranged reads (consolidated shuffle blobs) -----------------------------
    def get_range_buffers(self, parts: Sequence[Tuple[ObjectRef, int, int]]
                          ) -> List[bytes]:
        """Payload byte ranges: ``(ref, offset, size)`` per part, offsets
        relative to the payload. Control traffic is batched — ONE
        ``lookup_batch`` for all distinct refs (memo hits free), then one
        ``store_fetch_ranges`` RPC per remote payload host, fanned out on
        threads across distinct hosts; same-machine ranges are sliced out of
        the attached segment with no RPC at all. A vanished segment gets the
        standard one-fresh-lookup retry before escalating to
        :class:`ObjectLostError`."""
        if not parts:
            return []
        if self.remote:
            # compatibility path (shm-less client): one head-mediated fetch
            # per DISTINCT blob, sliced locally. Losses get the same typed
            # translation as _attach — a table miss must route into lineage
            # recovery, not fail the stage as a bare KeyError
            blobs: Dict[str, bytes] = {}
            for ref, _, _ in parts:
                if ref.id in blobs:
                    continue
                self.fetch_rpc_count += 1
                try:
                    data, _ = self._server.fetch_payload(ref.id)
                except ObjectLostError:
                    raise
                except (KeyError, FileNotFoundError) as e:
                    raise ObjectLostError(ref.id,
                                          "not in store table") from e
                except Exception as e:
                    if getattr(e, "exc_type", None) \
                            in _REMOTE_LOST_EXC_TYPES:
                        raise ObjectLostError(
                            ref.id, "blob unreachable: "
                            f"{getattr(e, 'message', e)}") from e
                    raise
                blobs[ref.id] = data
            return [bytes(blobs[ref.id][off:off + size])
                    for ref, off, size in parts]
        try:
            return self._get_ranges_once(parts, fresh=False)
        except ObjectLostError:
            raise
        except (FileNotFoundError, KeyError):
            # stale location (spill/fault-in moved the payload between our
            # lookup and read): one fresh lookup resolves the new home
            return self._get_ranges_once(parts, fresh=True)
        except Exception as e:
            if getattr(e, "exc_type", None) in _REMOTE_STALE_EXC_TYPES:
                return self._get_ranges_once(parts, fresh=True)
            raise

    def _get_ranges_once(self, parts: Sequence[Tuple[ObjectRef, int, int]],
                         fresh: bool) -> List[bytes]:
        ids = [ref.id for ref, _, _ in parts]
        entries = self.lookup_many(ids, fresh=fresh)
        missing = next((oid for oid in ids if oid not in entries), None)
        if missing is not None:
            self._evict(missing)
            raise ObjectLostError(missing, "not in store table")
        out: List[Optional[bytes]] = [None] * len(parts)
        # group remote ranges per payload host; local ones slice immediately.
        # Remote items carry (index, segment, base, start, size, oid): base
        # is the payload's table offset (arena offset / -1 for a dedicated
        # segment) and start the range offset within the payload — the
        # payload host needs both to route arena vs segment reads.
        groups: Dict[Optional[str],
                     List[Tuple[int, str, int, int, int, str]]] = {}
        for i, (ref, off, size) in enumerate(parts):
            segment, esize, kind, eoff, host_id, addr = entries[ref.id]
            if off + size > esize:
                raise ValueError(
                    f"range [{off}, {off + size}) exceeds payload size "
                    f"{esize} of object {ref.id}")
            if host_id == self.host_id:
                try:
                    # whole-payload view (zero-copy), then slice the range
                    view = self._local_view(ref.id, segment, eoff, esize)
                except FileNotFoundError:
                    if fresh:
                        # the segment is gone even after a fresh lookup: the
                        # blob is lost — surface the typed signal so lineage
                        # recovery regenerates instead of the consumer
                        # burning its retry budget on a repeating miss
                        self._evict(ref.id)
                        raise ObjectLostError(
                            ref.id, "segment vanished") from None
                    raise
                out[i] = bytes(view[off:off + size])
            else:
                groups.setdefault(addr, []).append(
                    (i, segment, eoff, off, size, ref.id))

        def _fetch_group(addr, items):
            ranges = [(seg, base, start, size)
                      for _, seg, base, start, size, _ in items]
            self.fetch_rpc_count += 1
            try:
                if addr:
                    chunks = self._peer(addr).call(
                        "store_fetch_ranges", ranges, timeout=60.0)
                else:  # payloads hosted on the head machine
                    chunks = self._server.fetch_ranges(ranges)
            except Exception as e:
                import concurrent.futures as _cf
                # KeyError covers a peer arena that no longer hosts the
                # segment (payload re-homed) — same stale-location shape as
                # a vanished dedicated segment
                if getattr(e, "exc_type", None) in _REMOTE_STALE_EXC_TYPES \
                        or isinstance(e, (FileNotFoundError, KeyError)):
                    if fresh:  # gone even after the fresh lookup: lost
                        for item in items:
                            self._evict(item[-1])
                        raise ObjectLostError(
                            items[0][-1],
                            f"payload vanished on {addr or 'head'}: {e}") \
                            from e
                    raise
                if isinstance(e, (OSError, _cf.TimeoutError, TimeoutError)) \
                        or type(e).__name__ == "ConnectionLost":
                    for item in items:
                        self._evict(item[-1])
                    raise ObjectLostError(
                        items[0][-1],
                        f"payload host {addr or 'head'} unreachable: {e}") \
                        from e
                raise
            for item, chunk in zip(items, chunks):
                out[item[0]] = chunk

        if len(groups) == 1:
            addr, items = next(iter(groups.items()))
            _fetch_group(addr, items)
        elif groups:
            import concurrent.futures as _cf
            with _cf.ThreadPoolExecutor(
                    max_workers=min(4, len(groups))) as pool:
                futs = [pool.submit(_fetch_group, addr, items)
                        for addr, items in groups.items()]
                for f in futs:
                    f.result()
        return out  # type: ignore[return-value]

    # -- pipelined-shuffle seal notifications ----------------------------------
    def stream_begin(self, stage_key: str, num_maps: int) -> None:
        self._server.stream_begin(stage_key, int(num_maps))

    def stream_publish(self, stage_key: str, map_id: int, gen: int,
                       ref_id: str, size: int,
                       index: Sequence[Sequence[int]]) -> None:
        self._server.stream_publish(stage_key, int(map_id), int(gen),
                                    ref_id, int(size), list(index))

    def stream_poll(self, stage_key: str, bucket: int,
                    have: Optional[Dict[int, int]] = None,
                    timeout_s: float = 10.0) -> Dict[str, Any]:
        """One seal-stream poll round. In-process (driver) callers get the
        server's DeferredReply and wait its future here; proxied callers
        (executors) receive the final dict — the head's RPC server resolves
        the deferred reply before the response frame ships."""
        self.stream_poll_count += 1
        res = self._server.stream_poll(stage_key, int(bucket),
                                       dict(have or {}), float(timeout_s))
        if isinstance(res, DeferredReply):
            res = res.future.result()
        return res

    def stream_abort(self, stage_key: str, message: str) -> None:
        self._server.stream_abort(stage_key, str(message))

    def stream_close(self, stage_keys: Sequence[str]) -> None:
        self._server.stream_close(list(stage_keys))

    # -- lifetime -------------------------------------------------------------
    def free(self, refs: List[ObjectRef]) -> int:
        """Release blobs; idempotent and duplicate-tolerant — a speculation
        loser's outputs can reach free() from the late-result drain AND a
        stage-abort sweep, and the store-count audits (chaos tests) rely on
        a double free never going negative or erroring. The server pop
        already ignores unknown ids; ids are deduped here so a batch with
        repeats evicts local memo/segment state exactly once."""
        ids = list(dict.fromkeys(r.id for r in refs))
        if not ids:
            return 0
        for oid in ids:
            self._evict(oid)
        self.meta_rpc_count += 1
        return self._server.free(ids)

    def transfer_ownership(self, refs: List[ObjectRef], new_owner: str) -> int:
        self.meta_rpc_count += 1
        return self._server.transfer_ownership([r.id for r in refs], new_owner)

    def contains(self, ref: ObjectRef) -> bool:
        self.meta_rpc_count += 1
        return self._server.contains(ref.id)

    def locations(self, refs: List[ObjectRef]) -> Dict[str, str]:
        """``object_id -> host_id`` (the machine holding each payload)."""
        self.meta_rpc_count += 1
        return self._server.locations([r.id for r in refs])

    def residency(self, refs: List[ObjectRef]) -> Dict[str, Tuple[str, str]]:
        """``object_id -> (host_id, tier)`` with tier ``"shm"`` or
        ``"spilled"`` — the engine's data-gravity locality source (the
        tier-blind twin is :meth:`locations`)."""
        self.meta_rpc_count += 1
        return self._server.residency([r.id for r in refs])

    def eviction_hints(self, pin: Optional[List[ObjectRef]] = None,
                       unpin: Optional[List[ObjectRef]] = None,
                       evict_first: Optional[List[ObjectRef]] = None
                       ) -> Dict[str, int]:
        """Push stage-aware eviction hints (pin the stage being consumed,
        evict-first what its consumers finished with). Policy-plane, not
        metadata-plane: deliberately NOT counted in ``meta_rpc_count``,
        so the benches' metadata-RPC comparisons measure the data plane
        unchanged."""
        return self._server.eviction_hints(
            [r.id for r in pin or ()],
            [r.id for r in unpin or ()],
            [r.id for r in evict_first or ()])

    def derive_budgets(self, measured_bytes: int) -> Dict[str, int]:
        """Re-derive per-host store budgets from measured stage bytes
        (policy-plane; uncounted like :meth:`eviction_hints`)."""
        return self._server.derive_budgets(int(measured_bytes))

    def stats(self) -> Dict[str, Any]:
        return self._server.stats()

    def rpc_counters(self) -> Dict[str, int]:
        """Control-plane calls this process issued: ``meta`` (table server),
        ``fetch`` (payload-fetch RPCs; zero on the pure local-shm path), and
        ``stream_poll`` (pipelined-shuffle seal polls — long waits, counted
        apart so they never pollute the metadata-plane comparisons)."""
        return {"meta": self.meta_rpc_count, "fetch": self.fetch_rpc_count,
                "stream_poll": self.stream_poll_count}

    def close(self) -> None:
        self._sweep_retired()
        with self._lock:
            for shm in self._attached.values():
                try:
                    shm.close()
                except Exception:
                    self._retired.append(shm)
            self._attached.clear()
            self._seg_of.clear()
            self._lookup_memo.clear()
            for client in self._peers.values():
                try:
                    client.close()
                except Exception:
                    pass
            self._peers.clear()
            # the write-arena mapping is deliberately NOT munmapped here: an
            # in-flight put_raw may still be writing through a view, and the
            # OS reclaims the mapping at process exit anyway
            self._arena = None
            self._arena_probed = False


# -- process-global client (set by head init / actor bootstrap) ---------------------
_client: Optional[ObjectStoreClient] = None


def set_client(client: Optional[ObjectStoreClient]) -> None:
    global _client
    _client = client


def get_client() -> ObjectStoreClient:
    if _client is None:
        raise RuntimeError(
            "no object store client in this process; call raydp_tpu.init() first")
    return _client
