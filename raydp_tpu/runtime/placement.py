"""Placement groups and the node/resource model.

Parity: Ray placement groups as used by the reference — ``init_spark`` pre-allocates
one ``{CPU, memory}`` bundle per executor and passes the group + bundle indexes down
to actor creation (reference context.py:119-140, RayAppMaster.scala:290-303
round-robins executors over bundles); the MPI subsystem uses ``STRICT_SPREAD`` to pin
one peer per node (mpi/mpi_job.py:192-222). TPU specifics: chips are host-granular —
a bundle that requests the ``TPU`` resource must land on a whole host (one JAX
process owns all chips of a host), so fractional TPU bundles are rejected.

Nodes here are *logical*: a single machine can register several virtual nodes to
simulate multi-host topologies in tests, the same trick the reference's test suite
plays with ``ray.cluster_utils.Cluster`` (test_spark_cluster.py:90-110).
"""

from __future__ import annotations

import enum
import itertools
import threading
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class PlacementStrategy(str, enum.Enum):
    PACK = "PACK"
    SPREAD = "SPREAD"
    STRICT_PACK = "STRICT_PACK"
    STRICT_SPREAD = "STRICT_SPREAD"


@dataclass
class NodeInfo:
    node_id: str
    address: str
    resources: Dict[str, float]
    available: Dict[str, float] = field(default_factory=dict)
    alive: bool = True

    def __post_init__(self):
        if not self.available:
            self.available = dict(self.resources)
        # every node carries its affinity label, parity with Ray's node:<ip>
        label = f"node:{self.address}"
        self.resources.setdefault(label, 1.0)
        self.available.setdefault(label, 1.0)


@dataclass
class Bundle:
    index: int
    resources: Dict[str, float]
    node_id: Optional[str] = None  # assigned at group creation


@dataclass
class PlacementGroup:
    group_id: str
    strategy: PlacementStrategy
    bundles: List[Bundle]
    created: bool = False

    def bundle_node(self, index: int) -> Optional[str]:
        return self.bundles[index].node_id


def group_from_dict(d: Dict) -> PlacementGroup:
    """Rebuild a PlacementGroup from its RPC wire form (head._group_to_dict):
    the client-mode driver works with the same dataclass the in-process
    runtime hands out."""
    return PlacementGroup(
        group_id=d["group_id"],
        strategy=PlacementStrategy(d["strategy"]),
        bundles=[Bundle(b["index"], dict(b["resources"]), b.get("node_id"))
                 for b in d["bundles"]],
        created=True,
    )


class ResourceManager:
    """Tracks logical nodes, allocates actor/bundle resources, places groups."""

    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: Dict[str, NodeInfo] = {}
        self._groups: Dict[str, PlacementGroup] = {}
        self._rr = itertools.count()

    # -- nodes ---------------------------------------------------------------
    def add_node(self, address: str, resources: Dict[str, float]) -> str:
        with self._lock:
            node_id = f"node-{len(self._nodes)}-{uuid.uuid4().hex[:6]}"
            self._nodes[node_id] = NodeInfo(node_id, address, dict(resources))
            return node_id

    def remove_node(self, node_id: str) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node:
                node.alive = False

    def nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self._nodes.values() if n.alive]

    def get_node(self, node_id: str) -> Optional[NodeInfo]:
        with self._lock:
            return self._nodes.get(node_id)

    # -- allocation ----------------------------------------------------------
    def _fits(self, node: NodeInfo, resources: Dict[str, float]) -> bool:
        if not node.alive:
            return False
        for k, v in resources.items():
            if v > 0 and node.available.get(k, 0.0) + 1e-9 < v:
                return False
        return True

    def _take(self, node: NodeInfo, resources: Dict[str, float]) -> None:
        for k, v in resources.items():
            if v > 0:
                node.available[k] = node.available.get(k, 0.0) - v

    def _give(self, node: NodeInfo, resources: Dict[str, float]) -> None:
        for k, v in resources.items():
            if v > 0:
                node.available[k] = node.available.get(k, 0.0) + v

    def allocate(self, resources: Dict[str, float],
                 node_id: Optional[str] = None) -> Optional[str]:
        """Reserve ``resources`` on a node (round-robin over feasible nodes when
        ``node_id`` is not pinned). Returns the node id, or None if infeasible."""
        with self._lock:
            if node_id is not None:
                node = self._nodes.get(node_id)
                if node is not None and self._fits(node, resources):
                    self._take(node, resources)
                    return node_id
                return None
            alive = [n for n in self._nodes.values() if n.alive]
            if not alive:
                return None
            start = next(self._rr) % len(alive)
            for i in range(len(alive)):
                node = alive[(start + i) % len(alive)]
                if self._fits(node, resources):
                    self._take(node, resources)
                    return node.node_id
            return None

    def release(self, node_id: str, resources: Dict[str, float]) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None:
                self._give(node, resources)

    # -- placement groups ----------------------------------------------------
    def create_group(self, bundles: List[Dict[str, float]],
                     strategy: PlacementStrategy) -> PlacementGroup:
        """Assign every bundle to a node per strategy, reserving resources.

        Raises ValueError if the group cannot be placed (parity: ``pg.ready()``
        would hang in Ray; we fail fast instead, context.py:133-140 waits then
        passes the group down).
        """
        with self._lock:
            for b in bundles:
                if 0 < b.get("TPU", 0) < 1:
                    raise ValueError(
                        "fractional TPU bundles are not placeable: TPU chips are "
                        "host-granular (one JAX process per host)")
            group = PlacementGroup(
                group_id=f"pg-{uuid.uuid4().hex[:8]}",
                strategy=PlacementStrategy(strategy),
                bundles=[Bundle(i, dict(b)) for i, b in enumerate(bundles)],
            )
            placed: List[Bundle] = []
            try:
                if group.strategy in (PlacementStrategy.STRICT_PACK,):
                    # all bundles on one node
                    total: Dict[str, float] = {}
                    for b in group.bundles:
                        for k, v in b.resources.items():
                            total[k] = total.get(k, 0.0) + v
                    node_id = self.allocate(total)
                    if node_id is None:
                        raise ValueError("STRICT_PACK group does not fit on any node")
                    for b in group.bundles:
                        b.node_id = node_id
                    placed = []  # released as a whole below if needed
                else:
                    used_nodes: set = set()
                    for b in group.bundles:
                        node_id = None
                        if group.strategy == PlacementStrategy.STRICT_SPREAD:
                            for n in self._nodes.values():
                                if n.node_id in used_nodes:
                                    continue
                                if self._fits(n, b.resources):
                                    node_id = n.node_id
                                    self._take(n, b.resources)
                                    break
                            if node_id is None:
                                raise ValueError(
                                    "STRICT_SPREAD group needs more nodes than available")
                        else:
                            node_id = self.allocate(b.resources)
                            if node_id is None:
                                raise ValueError("placement group bundle does not fit")
                        b.node_id = node_id
                        used_nodes.add(node_id)
                        placed.append(b)
            except ValueError:
                for b in placed:
                    self.release(b.node_id, b.resources)
                raise
            group.created = True
            self._groups[group.group_id] = group
            return group

    def get_group(self, group_id: str) -> Optional[PlacementGroup]:
        with self._lock:
            return self._groups.get(group_id)

    def remove_group(self, group_id: str) -> None:
        with self._lock:
            group = self._groups.pop(group_id, None)
        if group is not None:
            if group.strategy == PlacementStrategy.STRICT_PACK:
                total: Dict[str, float] = {}
                for b in group.bundles:
                    for k, v in b.resources.items():
                        total[k] = total.get(k, 0.0) + v
                if group.bundles and group.bundles[0].node_id:
                    self.release(group.bundles[0].node_id, total)
            else:
                for b in group.bundles:
                    if b.node_id:
                        self.release(b.node_id, b.resources)

    def groups(self) -> List[PlacementGroup]:
        with self._lock:
            return list(self._groups.values())
