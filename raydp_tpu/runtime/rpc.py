"""TCP RPC: length-prefixed cloudpickle request/response with multiplexing.

This is the control plane that replaces, in one mechanism, the reference's four
control channels (SURVEY.md §2.5): py4j driver↔gateway (ray_cluster_master.py:103-183),
Spark netty RpcEnv (RayAppMaster.scala:63-74), Ray actor RPC, and the MPI gRPC plane
(mpi/network/network.proto:22-37). One wire format, usable cross-host: frames are
``8-byte big-endian length || cloudpickle payload``.

Requests are ``(req_id, method, args, kwargs[, meta])``; responses
``(req_id, ok, value)`` where a failed call carries a :class:`RemoteError`
payload with the remote traceback. The optional fifth element is call
metadata — today the caller's causal-trace context
(``{"trace": (trace_id, parent_span_id)}``), which the dispatcher installs
in a ``contextvars`` context around the handler so remote spans record
their driver-side parentage (doc/observability.md). A four-element request
from a legacy/external caller dispatches unchanged. Responses may arrive
out of order — the client demultiplexes on ``req_id`` — so a server may
process calls concurrently (actors declare a ``max_concurrency``, parity
with RayExecutorUtils.java:60 ``setMaxConcurrency(2)``).
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import traceback
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

import cloudpickle

from raydp_tpu import faults

logger = logging.getLogger("raydp_tpu.rpc")

_LEN = struct.Struct(">Q")
_MAX_FRAME = 1 << 40


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    """Peer went away mid-call; used by supervisors to detect actor death."""


class RemoteError(RpcError):
    """An exception raised inside the remote handler, with its traceback.

    ``object_id`` rides along when the remote exception carried one (e.g.
    ``ObjectLostError``), so consumers key recovery on a structured field
    instead of parsing ids out of message text."""

    def __init__(self, exc_type: str, message: str, remote_traceback: str,
                 object_id: Optional[str] = None):
        super().__init__(f"{exc_type}: {message}\n--- remote traceback ---\n{remote_traceback}")
        self.exc_type = exc_type
        self.message = message
        self.remote_traceback = remote_traceback
        self.object_id = object_id

    def __reduce__(self):
        return (RemoteError, (self.exc_type, self.message,
                              self.remote_traceback, self.object_id))


def _send_frame(sock: socket.socket, payload: bytes, lock: threading.Lock) -> None:
    with lock:
        sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionLost("socket closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > _MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    return _recv_exact(sock, length)


class DeferredReply:
    """A handler's promise of a later result: the dispatcher thread returns
    immediately and the response frame is sent when ``future`` completes.

    This is how long waits (actor-ready, restart grace) avoid parking the
    bounded dispatch pool — a mass-restart flurry of waiters must not starve
    unrelated traffic such as store-table lookups (VERDICT r2 weak #4)."""

    def __init__(self, future: Future):
        self.future = future


class RpcServer:
    """Threaded RPC server dispatching requests to a handler object.

    ``handler(method: str, args, kwargs)`` resolves and runs the call. Dispatch
    happens on a bounded thread pool of size ``max_concurrency``; handlers
    returning :class:`DeferredReply` free their thread and complete later.
    """

    def __init__(
        self,
        handler: Callable[[str, tuple, dict], Any],
        host: str = "127.0.0.1",
        port: int = 0,
        max_concurrency: int = 8,
        name: str = "rpc",
    ):
        self._handler = handler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.address: Tuple[str, int] = self._sock.getsockname()
        self._pool = ThreadPoolExecutor(max_workers=max_concurrency,
                                        thread_name_prefix=f"{name}-dispatch")
        self._stopped = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{name}-accept", daemon=True)
        self._accept_thread.start()

    @property
    def url(self) -> str:
        return f"{self.address[0]}:{self.address[1]}"

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError as e:
                if not self._stopped.is_set():
                    logger.error("rpc server accept loop died: %r", e)
                return
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True).start()
            except Exception as e:  # e.g. thread-limit; keep accepting
                logger.error("rpc server failed to serve connection: %r", e)
                try:
                    conn.close()
                except OSError:
                    pass

    def _serve_conn(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        try:
            while not self._stopped.is_set():
                frame = _recv_frame(conn)
                req = cloudpickle.loads(frame)
                # tolerate the legacy 4-tuple: a caller without trace
                # metadata must dispatch exactly as before
                req_id, method, args, kwargs = req[:4]
                meta = req[4] if len(req) > 4 else None
                self._pool.submit(self._dispatch, conn, send_lock, req_id,
                                  method, args, kwargs, meta)
        except (ConnectionLost, OSError):
            pass
        except BaseException as e:  # noqa: BLE001 - diagnose, drop only this conn
            logger.error("rpc connection handler died: %r\n%s", e,
                         traceback.format_exc())
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, send_lock, req_id, method, args, kwargs,
                  meta=None) -> None:
        try:
            # install the caller's trace context for the handler body (and
            # anything the handler captures for worker threads / deferred
            # completions); reset before the pool thread moves on
            from raydp_tpu import profiler
            ctx = meta.get("trace") if isinstance(meta, dict) else None
            with profiler.activate(ctx):
                value = self._handler(method, args, kwargs)
            if isinstance(value, DeferredReply):
                # this dispatcher thread goes back to the pool now; the reply
                # is sent from a POOL thread at completion — never from the
                # completing thread itself (a supervisor resolving waiters
                # must not block in sendall on a stalled client socket)
                value.future.add_done_callback(
                    lambda fut: self._submit_reply(conn, send_lock, req_id,
                                                   fut))
                return
            payload = cloudpickle.dumps((req_id, True, value))
        except BaseException as e:  # noqa: BLE001 - must serialize any failure
            payload = self._error_payload(req_id, e)
        try:
            _send_frame(conn, payload, send_lock)
        except OSError:
            pass

    def _submit_reply(self, conn, send_lock, req_id, fut) -> None:
        try:
            self._pool.submit(self._send_reply, conn, send_lock, req_id, fut)
        except RuntimeError:  # pool already shut down: drop the reply
            pass

    def _send_reply(self, conn, send_lock, req_id, fut) -> None:
        try:
            payload = cloudpickle.dumps((req_id, True, fut.result()))
        except BaseException as e:  # noqa: BLE001 - must serialize any failure
            payload = self._error_payload(req_id, e)
        try:
            _send_frame(conn, payload, send_lock)
        except OSError:
            pass

    @staticmethod
    def _error_payload(req_id, e) -> bytes:
        oid = getattr(e, "object_id", None)
        oid = oid if isinstance(oid, str) else None
        err = RemoteError(type(e).__name__, str(e), traceback.format_exc(),
                          object_id=oid)
        try:
            return cloudpickle.dumps((req_id, False, err))
        except Exception:
            return cloudpickle.dumps(
                (req_id, False,
                 RemoteError(type(e).__name__, str(e), "<unpicklable>",
                             object_id=oid)))

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._pool.shutdown(wait=False)


class RpcClient:
    """Persistent connection to one RpcServer; thread-safe; demultiplexes responses."""

    def __init__(self, address: Tuple[str, int], connect_timeout: float = 10.0):
        self.address = tuple(address)
        self._sock = socket.create_connection(self.address, timeout=connect_timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = threading.Lock()
        self._pending_lock = threading.Lock()
        self._pending: Dict[int, Future] = {}  # guarded-by: _pending_lock
        self._next_id = 0  # guarded-by: _pending_lock
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                frame = _recv_frame(self._sock)
                req_id, ok, value = cloudpickle.loads(frame)
                with self._pending_lock:
                    fut = self._pending.pop(req_id, None)
                if fut is None:
                    continue
                if ok:
                    fut.set_result(value)
                else:
                    fut.set_exception(value)
        except (ConnectionLost, OSError, EOFError) as e:
            self._fail_all(ConnectionLost(f"connection to {self.address} lost: {e}"))

    def _fail_all(self, exc: Exception) -> None:
        self._closed = True
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)

    def submit(self, method: str, *args, **kwargs) -> Future:
        rule = faults.check("rpc.call", key=method)
        if rule is not None:
            if rule.action == "connloss":
                raise ConnectionLost(
                    f"injected connection loss to {self.address} "
                    f"on {method!r}")
            faults.apply(rule, "rpc.call")
        if self._closed:
            raise ConnectionLost(f"connection to {self.address} closed")
        fut: Future = Future()
        with self._pending_lock:
            req_id = self._next_id
            self._next_id += 1
            self._pending[req_id] = fut
        from raydp_tpu import profiler
        ctx = profiler.current_trace()
        payload = cloudpickle.dumps(
            (req_id, method, args, kwargs, {"trace": ctx})
            if ctx is not None else (req_id, method, args, kwargs))
        try:
            _send_frame(self._sock, payload, self._send_lock)
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            self._fail_all(ConnectionLost(str(e)))
            raise ConnectionLost(f"send to {self.address} failed: {e}") from e
        return fut

    def call(self, method: str, *args, timeout: Optional[float] = None, **kwargs) -> Any:
        return self.submit(method, *args, **kwargs).result(timeout=timeout)

    @property
    def local_host(self) -> str:
        """This process's address on the route to the server — the right host
        for services that peers across the same network must reach."""
        try:
            return self._sock.getsockname()[0]
        except OSError:
            return "127.0.0.1"

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


def connect_with_retry(address: Tuple[str, int], attempts: int = 6,
                       delay: float = 0.25) -> "RpcClient":
    """Connect and verify liveness with ``ping``, retrying transient failures.

    Bootstrap connections (actor → head, SPMD rank → driver) occasionally see
    ECONNRESET when ephemeral ports recycle across rapid session cycles; a
    fresh socket resolves it. Used only at process startup where every call is
    idempotent.
    """
    import time as _time

    last: Optional[Exception] = None
    for attempt in range(attempts):
        client = None
        try:
            client = RpcClient(address)
            client.call("ping", timeout=10.0)
            return client
        except Exception as e:  # noqa: BLE001 - retry any transient failure
            last = e
            if client is not None:
                client.close()
            _time.sleep(delay * (attempt + 1))
    raise ConnectionLost(f"could not reach {address} after {attempts} attempts: {last}")


class MethodDispatcher:
    """Maps RPC method names to bound methods of a target object.

    Methods starting with ``_`` are not callable remotely.
    """

    def __init__(self, target: Any):
        self._target = target

    def __call__(self, method: str, args: tuple, kwargs: dict) -> Any:
        if method.startswith("_"):
            raise AttributeError(f"method {method!r} is not remotely callable")
        fn = getattr(self._target, method, None)
        if fn is None or not callable(fn):
            # list the real surface: a typo'd call site fails with enough to
            # fix it, instead of a bare name echoed back through RemoteError.
            # Introspect the CLASS, not the instance — instance getattr
            # would execute property getters, and one that raises here would
            # mask the AttributeError (changing exc_type misroutes the
            # retry/recovery plane keyed on it)
            cls = type(self._target)
            surface = sorted(
                n for n in dir(cls)
                if not n.startswith("_")
                and callable(getattr(cls, n, None)))
            raise AttributeError(
                f"{cls.__name__} has no remote method "
                f"{method!r}; remote surface: {', '.join(surface) or '(empty)'}")
        return fn(*args, **kwargs)
