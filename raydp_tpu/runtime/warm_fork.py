"""Warm-start executor plane: fork workers from a pre-imported prototype.

Cold executor spawn pays a fresh interpreter plus the heavy import chain
(jax, pyarrow, pandas) on every scale-up — seconds of wall-clock between the
autoscaler's decision and a worker that can take tasks. This module keeps ONE
long-lived prototype process per spawner (the head's local spawn path, or a
node agent) that has already paid those imports, and serves each spawn by
``os.fork()``-ing the prototype: the child inherits the warm import state
copy-on-write and goes straight into the actor bootstrap
(:mod:`raydp_tpu.runtime.actor_main`). Parity: the reference rides Ray's
prestarted worker pool for exactly this reason (SURVEY.md §4 — executor
creation is on the job's critical path when AQE re-plans stage widths).

Topology and failure containment:

- The prototype is spawned with ``PR_SET_PDEATHSIG`` against its owner
  (driver or node agent), and every forked worker sets it against the
  prototype. A hard-killed driver therefore takes the prototype down, and the
  prototype's death takes its forked workers down: ZERO orphans, the same
  guarantee the cold path gets from process groups + agent pdeathsig. The
  deliberate flip side: a crashed prototype kills its living forked workers —
  that is node-death-shaped, the supervisor restarts them (cold, because the
  manager latches failed).
- Any warm-plane failure (prototype won't start, handshake timeout, protocol
  error) raises :class:`WarmForkError`; callers degrade LOUDLY to the cold
  spawn path (a warning plus a degraded ``warm_fork`` event) and the manager
  latches failed. The latch is supervised, not permanent: after
  ``RDT_WARM_REFRESH_COOLDOWN_S`` the next fork request re-warms a fresh
  prototype (bounded by ``RDT_WARM_FORK_RETRIES`` restarts, each counted by
  ``pool_warm_refreshes_total``), so long sessions stay fork-fast. Warm
  start is an accelerator, never a correctness dependency.
- A forked child that dies before its readiness handshake is reaped by the
  prototype's ``waitpid`` loop (no zombie) and reported dead through
  :meth:`WarmForkManager.poll_child` (no phantom ALIVE worker).

The prototype protocol is newline-delimited JSON over stdin/stdout:
``{"op": "fork", "env": {...}, "log": path}`` → ``{"pid": n}``;
``{"op": "poll", "pid": n}`` → ``{"exit": code|null}``; ``{"op": "ping"}``.
The prototype stays single-threaded (fork safety) and reaps exited children
opportunistically on every loop tick.
"""

from __future__ import annotations

import json
import os
import select
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

from raydp_tpu import faults, knobs, metrics
from raydp_tpu.log import get_logger

logger = get_logger("warm_fork")

try:  # load libc at import: CDLL post-fork can deadlock in a threaded parent
    import ctypes

    _LIBC = ctypes.CDLL("libc.so.6", use_errno=True)
except Exception:  # pragma: no cover - non-glibc platform
    _LIBC = None


def _set_pdeathsig() -> None:
    """PR_SET_PDEATHSIG(SIGKILL): die with the parent. Applied twice along
    the chain (owner→prototype, prototype→worker) so a hard-killed owner
    cascades all the way down — the zero-orphan invariant of the warm plane."""
    if _LIBC is not None:
        _LIBC.prctl(1, signal.SIGKILL)  # 1 = PR_SET_PDEATHSIG


class WarmForkError(RuntimeError):
    """The warm plane is unavailable; callers fall back to cold spawn."""


class _LineReader:
    """Deadline-bounded newline framing over a raw fd (no buffered reader:
    the poller must see exactly what we have not consumed). ``poll``, not
    ``select``: in a long-lived owner the pipe fd can land past FD_SETSIZE
    (1024), where ``select`` hard-fails with ValueError."""

    def __init__(self, fd: int):
        self._fd = fd
        self._buf = b""
        self._poll = select.poll()
        self._poll.register(fd, select.POLLIN)

    def readline(self, timeout: float) -> Optional[bytes]:
        """One line without its newline; None on timeout, b"" on EOF."""
        deadline = time.monotonic() + timeout
        while b"\n" not in self._buf:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            ready = self._poll.poll(min(remaining, 1.0) * 1000.0)
            if not ready:
                continue
            chunk = os.read(self._fd, 65536)
            if not chunk:
                return b""
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return line


# ---- prototype process (python -m raydp_tpu.runtime.warm_fork) ---------------


def _preimport() -> list:
    """Pay the heavy imports once, in the prototype. A module that fails to
    import is skipped with a warning — the fork still works, just colder."""
    names = []
    spec = str(knobs.get("RDT_WARM_IMPORTS") or "")
    for name in (n.strip() for n in spec.split(",")):
        if not name:
            continue
        try:
            __import__(name)
            names.append(name)
        except Exception as e:
            print(f"warm-fork prototype: import {name} failed: {e}",
                  file=sys.stderr, flush=True)
    return names


def _child_exec(env: Dict[str, str], log_path: str) -> None:
    """Runs in the forked worker: become what an exec'd actor_main would be.
    Only this child's thread survives the fork, so state is rebuilt, not
    trusted: fresh session, new env, reseeded PRNG, re-armed fault plane."""
    os.setsid()  # own process group: the owner's killpg(pid) contract holds
    _set_pdeathsig()  # against the PROTOTYPE: its death reaps this worker
    os.environ.clear()
    os.environ.update(env)
    os.environ["RDT_WARM_FORKED"] = "1"  # spawn provenance for telemetry
    # wire stdio the way the cold Popen does: log file out, devnull in
    fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    os.dup2(fd, 1)
    os.dup2(fd, 2)
    if fd > 2:
        os.close(fd)
    devnull = os.open(os.devnull, os.O_RDONLY)
    os.dup2(devnull, 0)
    if devnull > 2:
        os.close(devnull)
    # an exec would honor PYTHONPATH; a fork must splice it into sys.path
    # (cloudpickle resolves driver classes by reference)
    for p in reversed((env.get("PYTHONPATH") or "").split(os.pathsep)):
        if p and p not in sys.path:
            sys.path.insert(0, p)
    import random

    random.seed()  # forked twins must not share a PRNG stream
    faults.reset()  # re-arm from THIS worker's env, not the prototype's
    metrics.reset()  # a fresh process starts with fresh counters
    rc = 0
    try:
        from raydp_tpu.runtime import actor_main

        actor_main.main()
    except SystemExit as e:
        rc = int(e.code or 0)
    except BaseException:
        import traceback

        traceback.print_exc()
        rc = 1
    finally:
        # skip interpreter finalization: atexit/threads belong to the
        # prototype image, not this worker
        os._exit(rc)


def prototype_main() -> None:
    _set_pdeathsig()  # against the owner (driver/agent): die with it
    imports = _preimport()
    exits: Dict[int, int] = {}

    def _reap() -> None:
        while True:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                return
            if pid == 0:
                return
            exits[pid] = os.waitstatus_to_exitcode(status)

    def _reply(obj) -> None:
        os.write(1, (json.dumps(obj) + "\n").encode())

    _reply({"ready": True, "pid": os.getpid(), "imports": imports})
    reader = _LineReader(0)
    while True:
        line = reader.readline(timeout=1.0)
        _reap()  # every tick: a pre-readiness death never lingers as a zombie
        if line is None:
            continue
        if line == b"":
            break  # owner closed the pipe: clean shutdown
        try:
            req = json.loads(line)
            op = req.get("op")
            if op == "fork":
                env = {str(k): str(v) for k, v in req["env"].items()}
                pid = os.fork()
                if pid == 0:
                    _child_exec(env, req["log"])  # never returns
                _reply({"pid": pid})
            elif op == "poll":
                pid = int(req["pid"])
                code = exits.get(pid)
                if code is None:
                    try:
                        wpid, status = os.waitpid(pid, os.WNOHANG)
                        if wpid == pid:
                            code = os.waitstatus_to_exitcode(status)
                            exits[pid] = code
                    except ChildProcessError:
                        code = -1  # not our child: report dead
                _reply({"exit": code})
            elif op == "ping":
                _reply({"ok": True})
            else:
                _reply({"error": f"unknown op {op!r}"})
        except SystemExit:
            raise
        except BaseException as e:  # a broken request must not kill the plane
            _reply({"error": repr(e)})


# ---- manager (lives in the spawner: head local path or node agent) -----------


class ForkedChild:
    """Popen-shaped handle to a warm-forked worker (a grandchild, so only the
    prototype can ``waitpid`` it — poll routes through the manager). Matches
    every surface the supervisor/agent code touches on a cold Popen:
    ``pid``, ``returncode``, ``poll``, ``wait``, ``kill``/``terminate``."""

    def __init__(self, manager: "WarmForkManager", pid: int):
        self._manager = manager
        self.pid = pid
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is None:
            self.returncode = self._manager.poll_child(self.pid)
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired("warm-fork-child", timeout)
            time.sleep(0.05)
        return self.returncode  # type: ignore[return-value]

    def kill(self) -> None:
        try:
            os.killpg(self.pid, signal.SIGKILL)  # child setsid()s: pgid==pid
        except (ProcessLookupError, PermissionError):
            try:
                os.kill(self.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    terminate = kill


class WarmForkManager:
    """Owns one prototype process and serves fork-fast spawns from it.

    Failure latch + supervised refresh: a start/protocol failure marks the
    manager failed — forks inside the latch raise immediately and the caller
    cold-spawns (a flapping prototype must not turn scale-up into a retry
    storm). But the latch is no longer permanent: once
    ``RDT_WARM_REFRESH_COOLDOWN_S`` has passed, the next fork request
    re-warms a fresh prototype (a ``warm_fork`` re-warm event +
    ``pool_warm_refreshes_total``), bounded by ``RDT_WARM_FORK_RETRIES``
    restarts per manager — long sessions return to fork-fast instead of
    paying cold spawns forever after one transient prototype death."""

    def __init__(self, log_dir: str):
        self._log_dir = log_dir
        self._lock = threading.Lock()
        self._proc: Optional[subprocess.Popen] = None
        self._reader: Optional[_LineReader] = None
        self._ready = False
        self._failed = False
        self._failed_at = 0.0
        self._refreshes = 0

    # ---- lifecycle ----------------------------------------------------------
    def _ensure_started(self) -> None:
        if self._proc is not None or self._failed:
            return
        os.makedirs(self._log_dir, exist_ok=True)
        log = open(os.path.join(self._log_dir, "warm-fork-prototype.out"),
                   "ab")
        try:
            self._proc = subprocess.Popen(
                [sys.executable, "-m", "raydp_tpu.runtime.warm_fork"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=log,
                start_new_session=True, env=dict(os.environ))
        finally:
            log.close()
        self._reader = _LineReader(self._proc.stdout.fileno())
        logger.info("warm-fork prototype started (pid %d)", self._proc.pid)

    def _await_ready(self, timeout: float) -> None:
        if self._ready:
            return
        line = self._reader.readline(timeout=timeout)
        if not line:  # timeout or EOF: either way the plane is unusable
            raise WarmForkError(
                f"prototype not ready within {timeout:.1f}s")
        handshake = json.loads(line)
        if not handshake.get("ready"):
            raise WarmForkError(f"bad prototype handshake: {handshake!r}")
        self._ready = True
        logger.info("warm-fork prototype ready (imports: %s)",
                    ",".join(handshake.get("imports", [])) or "none")

    def _request(self, obj, timeout: float = 10.0):
        try:
            self._proc.stdin.write((json.dumps(obj) + "\n").encode())
            self._proc.stdin.flush()
        except (OSError, ValueError) as e:
            raise WarmForkError(f"prototype pipe write failed: {e}") from e
        line = self._reader.readline(timeout=timeout)
        if not line:
            raise WarmForkError("prototype stopped answering")
        reply = json.loads(line)
        if "error" in reply:
            raise WarmForkError(f"prototype error: {reply['error']}")
        return reply

    def _fail(self) -> None:
        """Latch failed and put the prototype down; its pdeathsig'd children
        go with it, which the supervisor sees as worker death and restarts
        through the cold path."""
        self._failed = True
        self._failed_at = time.monotonic()
        self._ready = False
        proc, self._proc = self._proc, None
        if proc is not None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            proc.wait(timeout=5.0)

    def _refresh_allowed(self) -> bool:
        """May a latched-failed plane re-warm a prototype NOW? Bounded by
        RDT_WARM_FORK_RETRIES restarts per manager, rate-limited by
        RDT_WARM_REFRESH_COOLDOWN_S since the latch (requests inside the
        cooldown cold-spawn rather than hammer a crashing prototype)."""
        if not self._failed:
            return False
        if self._refreshes >= max(0, int(knobs.get("RDT_WARM_FORK_RETRIES"))):
            return False
        cooldown = max(0.0, float(knobs.get("RDT_WARM_REFRESH_COOLDOWN_S")))
        return time.monotonic() - self._failed_at >= cooldown

    @property
    def available(self) -> bool:
        return not self._failed or self._refresh_allowed()

    # ---- spawn path ---------------------------------------------------------
    def fork(self, env: Dict[str, str], log_path: str,
             key: str = "") -> ForkedChild:
        """Fork one worker with ``env`` writing to ``log_path``. Raises
        :class:`WarmForkError` when the plane is down — the caller's cue to
        cold-spawn. Chaos: ``pool.fork`` fires here; the ``crash`` action
        kills the fresh fork BEFORE its readiness handshake (modeling a
        worker that dies in bootstrap), other actions degrade the fork
        itself (``raise`` → cold-spawn fallback, ``delay`` → slow plane)."""
        rule = faults.check("pool.fork", key=key)
        kill_after = rule is not None and rule.action == "crash"
        if rule is not None and not kill_after:
            faults.apply(rule, "pool.fork")
        with self._lock:
            if self._failed:
                if not self._refresh_allowed():
                    raise WarmForkError("warm-fork plane is latched failed")
                # supervised prototype restart: clear the latch and let
                # _ensure_started below warm a fresh prototype — fork-fast
                # returns without a new manager
                self._refreshes += 1
                self._failed = False
                self._ready = False
                self._proc = None
                self._reader = None
                logger.warning("warm-fork plane re-warming prototype "
                               "(refresh %d)", self._refreshes)
                metrics.inc("pool_warm_refreshes_total")
                metrics.record_event("warm_fork", rewarm=True,
                                     refresh=self._refreshes, key=key)
            if self._proc is not None and self._proc.poll() is not None:
                logger.warning("warm-fork prototype died (exit %s)",
                               self._proc.returncode)
                self._fail()
                raise WarmForkError("prototype died")
            try:
                self._ensure_started()
                self._await_ready(float(knobs.get("RDT_WARM_FORK_WAIT_S")))
                reply = self._request({"op": "fork", "env": env,
                                       "log": log_path})
            except WarmForkError:
                self._fail()
                raise
            pid = int(reply["pid"])
        if kill_after:
            try:
                os.killpg(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        metrics.inc("pool_warm_forks_total")
        metrics.record_event("warm_fork", pid=pid, key=key,
                             injected_death=kill_after)
        return ForkedChild(self, pid)

    def poll_child(self, pid: int) -> Optional[int]:
        with self._lock:
            if self._proc is not None and self._ready and not self._failed:
                try:
                    return self._request({"op": "poll", "pid": pid})["exit"]
                except WarmForkError:
                    self._fail()
        # prototype gone: its pdeathsig killed the child — probe to confirm
        try:
            os.kill(pid, 0)
            return None  # still exiting (or pdeathsig mid-flight)
        except ProcessLookupError:
            return -9
        except PermissionError:  # pragma: no cover - pid reuse by other user
            return -9

    def stop(self) -> None:
        """Shutdown-time teardown. Living forked workers die with the
        prototype (pdeathsig) — call only after the spawner has terminated
        its workers, exactly like killing a node agent last."""
        with self._lock:
            proc, self._proc = self._proc, None
            self._ready = False
            if proc is None:
                return
            try:
                proc.stdin.close()  # EOF: clean prototype exit
            except OSError:
                pass
            try:
                proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    proc.kill()
                proc.wait(timeout=5.0)


def warm_spawn(manager_ref: list, log_dir: str, env: Dict[str, str],
               log_path: str, key: str) -> Optional[ForkedChild]:
    """Shared spawn-side glue for the head and the node agent: lazily create
    the manager in ``manager_ref[0]``, try a warm fork, and degrade loudly
    (warning + ``warm_fork`` degraded event) to None — the caller's cue to
    cold-spawn. Never raises."""
    try:
        if manager_ref[0] is None:
            manager_ref[0] = WarmForkManager(log_dir)
        if not manager_ref[0].available:
            return None
        return manager_ref[0].fork(env, log_path, key=key)
    except WarmForkError as e:
        logger.warning("warm fork for %s degraded to cold spawn: %s", key, e)
        metrics.record_event("warm_fork", key=key, degraded=True,
                             error=str(e))
        return None
    except Exception as e:  # pragma: no cover - defensive: never block spawns
        logger.warning("warm fork for %s failed unexpectedly (%s); "
                       "cold spawn", key, e)
        metrics.record_event("warm_fork", key=key, degraded=True,
                             error=repr(e))
        return None


if __name__ == "__main__":
    prototype_main()
