"""The serving plane: trained estimators behind a micro-batched, hedged
inference service over the executor pool (doc/serving.md).

    est.fit_on_frame(train_df)
    est.export_serving("/shared/model-v1")
    with ServingSession("/shared/model-v1", session=session) as srv:
        preds = srv.predict(rows)
        srv.autoscale()                    # replicas follow queue depth
        srv.rollout("/shared/model-v2")    # guarded canary deploy
"""

from raydp_tpu.serve.autoscale import ServingAutoscaler  # noqa: F401
from raydp_tpu.serve.rollout import RolloutController  # noqa: F401
from raydp_tpu.serve.servable import (  # noqa: F401
    Servable, export_bundle, load_servable,
)
from raydp_tpu.serve.session import (  # noqa: F401
    ServingError, ServingOverloaded, ServingSession,
)

__all__ = ["RolloutController", "Servable", "ServingAutoscaler",
           "ServingError", "ServingOverloaded", "ServingSession",
           "export_bundle", "load_servable"]
