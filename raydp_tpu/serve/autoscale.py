"""Serving replica autoscale: queue-depth-driven capacity, shed-free bursts.

:class:`ServingAutoscaler` is the serving-plane twin of
:class:`~raydp_tpu.etl.autoscale.PoolAutoscaler` — the same
sustained-window + cooldown controller shape, pointed at
:meth:`ServingSession.serving_report` instead of ``pool.load()``:

- **grow** when dispatch pressure persists for ``RDT_SERVE_SCALE_UP_S``:
  queue depth beyond what the current replicas can hold in flight
  (``replicas × RDT_SERVE_MAX_INFLIGHT``), or the outstanding-request
  count past half of ``RDT_SERVE_MAX_QUEUE`` — the point of scaling on
  queue depth is to add capacity BEFORE the shed path
  (:class:`~raydp_tpu.serve.session.ServingOverloaded`) fires, so the
  half-full admission queue is itself a pressure signal.
- **shrink** when the session has been fully idle (zero queued, zero
  outstanding) for ``RDT_SERVE_SCALE_IDLE_S``, through the retire path —
  drained replicas finish their in-flight dispatches before unloading.
- **hysteresis**: ``RDT_SERVE_SCALE_COOLDOWN_S`` after any event plus the
  sustained windows, so scale-up and the burst it absorbs cannot chase
  each other. Windows update even during the cooldown (a queue that
  builds mid-cooldown acts the moment it ends).

The actuator is :meth:`ServingSession.scale_replicas`, which sets EVERY
live version group to the same count — a mid-rollout canary scales with
the baseline, so it is never capacity-starved into a latency verdict.
Every knob is re-read per tick (the per-action contract of
doc/dev_lint.md).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from raydp_tpu import knobs, metrics
from raydp_tpu.log import get_logger

logger = get_logger("serve.autoscale")

__all__ = ["ServingAutoscaler"]


class ServingAutoscaler:
    """Grow/shrink a serving session's per-version replica counts from its
    dispatch queue depth. Construct via :meth:`ServingSession.autoscale`.
    ``events`` is a bounded in-order record of every scale decision
    ({ts, direction, replicas, reason}) — what the bench and tests
    assert on."""

    def __init__(self, serving, min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None):
        self._serving = serving
        self._min_arg = min_replicas
        self._max_arg = max_replicas
        mn, mx = self._bounds()
        if mx < max(1, mn):
            raise ValueError(
                f"serving autoscale needs max >= min >= 1 (got min={mn}, "
                f"max={mx}); set RDT_SERVE_MAX_REPLICAS or pass "
                "max_replicas=")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cooldown_until = 0.0
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self.events: List[Dict[str, Any]] = []
        self._events_cap = 256

    # ---- knob views (re-read per tick) --------------------------------------
    def _bounds(self) -> tuple:
        mn = self._min_arg if self._min_arg is not None \
            else int(knobs.get("RDT_SERVE_MIN_REPLICAS"))
        mx = self._max_arg if self._max_arg is not None \
            else int(knobs.get("RDT_SERVE_MAX_REPLICAS"))
        return max(1, mn), mx

    # ---- lifecycle ----------------------------------------------------------
    def start(self) -> "ServingAutoscaler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"rdt-serve-autoscaler-{self._serving.name}")
        self._thread.start()
        logger.info("serving autoscaler started (min=%d, max=%d)",
                    *self._bounds())
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(
                max(0.05,
                    float(knobs.get("RDT_SERVE_SCALE_INTERVAL_S")))):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - the controller must survive
                logger.exception("serving autoscale tick failed; continuing")

    # ---- one decision -------------------------------------------------------
    def _tick(self) -> None:
        srv = self._serving
        if srv._closed:
            return
        rep = srv.serving_report()
        now = time.monotonic()
        # the PRIMARY group's replica count is the session's size (the
        # actuator keeps every group at the same count, so any group reads
        # the same — but a mid-scale add lands group by group)
        primary = next((v for v in rep.get("versions", [])
                        if v.get("primary")), None)
        if primary is None:
            return
        replicas = primary["replicas"]
        depth = rep["queue_depth"]
        outstanding = rep["outstanding"]
        capacity = replicas * max(1, rep.get("max_inflight", 1))
        max_queue = rep.get("max_queue", 0)
        mn, mx = self._bounds()
        pressure = depth > capacity or (max_queue > 0
                                        and outstanding >= max_queue // 2)
        # sustained-signal windows update even inside the cooldown, so a
        # burst that builds DURING the cooldown acts the moment it ends
        if pressure:
            self._pressure_since = self._pressure_since or now
            self._idle_since = None
        elif depth == 0 and outstanding == 0:
            self._idle_since = self._idle_since or now
            self._pressure_since = None
        else:
            self._pressure_since = None
            self._idle_since = None
        if now < self._cooldown_until:
            return
        if self._pressure_since is not None and replicas < mx \
                and now - self._pressure_since \
                >= float(knobs.get("RDT_SERVE_SCALE_UP_S")):
            self._grow(replicas, depth, outstanding)
        elif self._idle_since is not None and replicas > mn \
                and now - self._idle_since \
                >= float(knobs.get("RDT_SERVE_SCALE_IDLE_S")):
            self._shrink(replicas)

    def _note(self, direction: str, replicas: int, reason: str) -> None:
        self._cooldown_until = time.monotonic() + \
            float(knobs.get("RDT_SERVE_SCALE_COOLDOWN_S"))
        self._pressure_since = None
        self._idle_since = None
        ev = {"ts": time.time(), "direction": direction,
              "replicas": replicas, "reason": reason}
        self.events.append(ev)
        del self.events[:-self._events_cap]
        metrics.record_event("serve_scale", session=self._serving.name,
                             direction=direction, replicas=replicas,
                             reason=reason)

    def _grow(self, replicas: int, depth: int, outstanding: int) -> None:
        reason = f"queue_depth={depth} outstanding={outstanding}"
        logger.info("serving autoscale: growing %s replicas %d -> %d (%s)",
                    self._serving.name, replicas, replicas + 1, reason)
        try:
            self._serving.scale_replicas(replicas + 1)
        except Exception:  # noqa: BLE001 - retried at the cooldown cadence
            # a failed load (executor mid-restart) pays the cooldown too:
            # a broken control plane is retried at the hysteresis cadence,
            # never every tick
            logger.warning("serving autoscale grow failed", exc_info=True)
            self._note("up-failed", replicas, reason)
            return
        metrics.inc("serve_scaled_up_total")
        self._note("up", replicas + 1, reason)

    def _shrink(self, replicas: int) -> None:
        logger.info("serving autoscale: draining %s replicas %d -> %d "
                    "(idle)", self._serving.name, replicas, replicas - 1)
        try:
            self._serving.scale_replicas(replicas - 1)
        except Exception:  # noqa: BLE001 - retried at the cooldown cadence
            logger.warning("serving autoscale shrink failed", exc_info=True)
            self._note("down-failed", replicas, "idle")
            return
        metrics.inc("serve_scaled_down_total")
        self._note("down", replicas - 1, "idle")
