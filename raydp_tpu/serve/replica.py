"""Executor-resident serving replicas (the process half of the serving plane).

One executor actor can host one or more replicas of a servable. Each replica
owns:

- a request queue fed by ``EtlExecutor.serve_predict`` — the dispatcher
  thread only enqueues and returns a
  :class:`~raydp_tpu.runtime.rpc.DeferredReply`, so a slow model can never
  park the actor's bounded RPC dispatch pool (the same rule the pipelined
  shuffle's streaming tasks follow; rdtlint's dispatcher-blocking rule
  checks it);
- a staging :class:`~raydp_tpu.data.feed.DevicePrefetcher`: Arrow decode +
  host staging + ``device_put`` for batch ``k+1`` run on the prefetcher
  thread while the worker thread runs the jitted apply of batch ``k`` —
  the PR 1 overlap, repurposed for inference;
- a dedicated worker thread running the applies in arrival order and
  completing each request's Future (which sends the RPC response).

The ``serve.predict`` fault site fires on the worker thread with key
``"<executor name>|<replica id>"`` — ``match=|<replica id>`` pins a chaos
rule to one replica (a seeded straggler for the hedging bench, a crash for
the re-route chaos leg) without touching its siblings.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict

import pyarrow as pa

from raydp_tpu import faults, knobs, profiler
from raydp_tpu.log import get_logger
from raydp_tpu.serve.servable import Servable, load_servable

logger = get_logger("serve.replica")


class ReplicaNotLoaded(KeyError):
    """``serve_predict`` hit a replica id this process does not hold — the
    executor restarted (fresh process, empty registry) or load never ran.
    The driver keys on this ``exc_type`` to re-route the request through the
    hedge path and reload the replica in the background."""


class _StopItem:
    pass


_STOP = _StopItem()


class _Replica:
    """One loaded servable + its staging pipeline and worker thread."""

    def __init__(self, replica_id: str, export_dir: str, actor_name: str,
                 prefetch: int):
        self.replica_id = replica_id
        self.export_dir = export_dir
        self.actor_name = actor_name
        self.servable: Servable = load_servable(export_dir)
        self._q: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self.batches = 0        # guarded-by: _lock
        self.rows = 0           # guarded-by: _lock
        self.requests = 0       # guarded-by: _lock
        self.apply_s = 0.0      # guarded-by: _lock
        self.queue_peak = 0     # guarded-by: _lock
        self._stopped = False
        self._prefetch = max(1, prefetch)
        self._worker = threading.Thread(
            target=self._serve_loop, daemon=True,
            name=f"rdt-serve-{replica_id}")
        self._worker.start()

    # -- dispatcher side (RPC thread): enqueue only ---------------------------
    def submit(self, payload: bytes) -> Future:
        fut: Future = Future()
        # the RPC dispatcher thread holds the driver's trace context (the
        # serve:batch span); the prefetcher and worker threads that carry
        # this request forward cannot inherit it — capture it into the
        # queue item so the staging decode and the jitted apply trace as
        # children of the driver dispatch
        ctx = profiler.capture()
        with self._lock:
            if self._stopped:
                raise ReplicaNotLoaded(
                    f"replica {self.replica_id} is unloaded")
            self.requests += 1
            depth = self._q.qsize() + 1
            self.queue_peak = max(self.queue_peak, depth)
            # enqueue under the lock: stop() also holds it to append the
            # stop sentinel, so a request can never land BEHIND the
            # sentinel (its future would silently never complete — the
            # queue is unbounded, so the put cannot block here)
            self._q.put((payload, fut, ctx))
        return fut

    # -- staging (DevicePrefetcher thread) ------------------------------------
    def _items(self):
        while True:
            item = self._q.get()
            if isinstance(item, _StopItem):
                return
            yield item

    def _stage(self, item):
        """decode + place one request's batch; a per-item failure rides to
        the worker attached to ITS future instead of killing the pipeline."""
        payload, fut, ctx = item
        try:
            with profiler.activate(ctx):
                table = pa.ipc.open_stream(pa.py_buffer(payload)).read_all()
                placed = self.servable.place(self.servable.decode(table))
            return placed, table.num_rows, fut, ctx, None
        except BaseException as e:  # noqa: BLE001 - belongs to this request
            return None, 0, fut, ctx, e

    # -- apply (worker thread) ------------------------------------------------
    def _serve_loop(self) -> None:
        from raydp_tpu.data.feed import DevicePrefetcher

        staged = DevicePrefetcher(
            self._items(), fn=self._stage, depth=self._prefetch,
            name=f"rdt-serve-stage-{self.replica_id}")
        for placed, rows, fut, ctx, err in staged:
            if err is not None:
                fut.set_exception(err)
                continue
            try:
                # the chaos plane's serving hook: a delay here models a slow
                # replica (what hedging exists for); a raise fails this one
                # request into the driver's re-route path; a crash is the
                # executor-died case (the actor supervisor restarts the
                # process and the driver reloads the replica)
                rule = faults.check(
                    "serve.predict",
                    key=f"{self.actor_name}|{self.replica_id}")
                if rule is not None:
                    faults.apply(rule, "serve.predict")
                t0 = time.perf_counter()
                with profiler.activate(ctx), \
                        profiler.trace("serve:apply", "serve",
                                       replica=self.replica_id, rows=rows):
                    preds = self.servable.apply(placed)
                dt = time.perf_counter() - t0
                with self._lock:
                    self.batches += 1
                    self.rows += rows
                    self.apply_s += dt
                fut.set_result(preds)
            except BaseException as e:  # noqa: BLE001 - serialize any failure
                fut.set_exception(e)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "replica": self.replica_id,
                "requests": self.requests,
                "batches": self.batches,
                "rows": self.rows,
                "apply_s": round(self.apply_s, 4),
                "queue_peak": self.queue_peak,
                "model_nbytes": self.servable.nbytes,
            }

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._q.put(_STOP)


_registry_lock = threading.Lock()
_registry: Dict[str, _Replica] = {}  # guarded-by: _registry_lock


def load(replica_id: str, export_dir: str, actor_name: str) -> Dict[str, Any]:
    """(Re)load a replica in this process. Idempotent per (id, dir): a
    duplicate load of the same bundle keeps the live replica (a racing
    driver-side reload after a transient error must not tear down a serving
    pipeline mid-request); a different dir replaces it."""
    prefetch = int(knobs.get("RDT_SERVE_PREFETCH"))
    with _registry_lock:
        old = _registry.get(replica_id)
        if old is not None and old.export_dir == export_dir:
            return old.stats()
    rep = _Replica(replica_id, export_dir, actor_name, prefetch)
    with _registry_lock:
        old = _registry.get(replica_id)
        if old is not None and old.export_dir == export_dir:
            # two same-bundle loads raced (a reload probe vs a session
            # init): keep the replica already serving traffic — replacing
            # it would stop a live pipeline mid-request — and retire the
            # fresh idle twin instead
            keep, loser = old, rep
        else:
            _registry[replica_id] = rep
            keep, loser = rep, old
    if loser is not None:
        loser.stop()
    if keep is rep:
        logger.info("loaded serving replica %s from %s (%d weight bytes)",
                    replica_id, export_dir, rep.servable.nbytes)
    return keep.stats()


def predict(replica_id: str, payload: bytes):
    """Enqueue one encoded batch; returns a DeferredReply completing with
    the prediction array. Runs on an RPC dispatcher thread: enqueue only."""
    from raydp_tpu.runtime.rpc import DeferredReply

    with _registry_lock:
        rep = _registry.get(replica_id)
    if rep is None:
        raise ReplicaNotLoaded(
            f"replica {replica_id} is not loaded in this process (executor "
            "restarted, or serve_load never ran here)")
    return DeferredReply(rep.submit(payload))


def unload(replica_id: str) -> bool:
    with _registry_lock:
        rep = _registry.pop(replica_id, None)
    if rep is not None:
        rep.stop()
    return rep is not None


def stats() -> Dict[str, Any]:
    with _registry_lock:
        reps = list(_registry.values())
    return {"replicas": [r.stats() for r in reps]}
