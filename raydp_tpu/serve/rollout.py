"""Guarded rollouts: canary traffic, metrics-driven judgment, auto-rollback.

:class:`RolloutController` turns :meth:`ServingSession.hot_swap`'s cliff
(100% of traffic the instant the load lands) into a guarded ramp:

1. **load** — the new servable joins the session as a live version group
   at ``RDT_SERVE_CANARY_WEIGHT`` of dispatch traffic
   (:meth:`ServingSession.load_version`).
2. **ramp** — the weight steps through ``RDT_SERVE_ROLLOUT_RAMP``
   (e.g. ``0.25,0.5,1.0``), holding each step for up to
   ``RDT_SERVE_ROLLOUT_STEP_S`` while the judgment window fills.
3. **judge** — at every poll the canary's per-version error-rate and p99
   (``serving_report()["versions"]`` — the windows the tentpole keeps per
   version precisely so a healthy baseline cannot mask a regressing
   canary) are compared against the baseline's over the SAME step:
   unhealthy when the canary's error rate exceeds the baseline's by more
   than ``RDT_SERVE_ROLLOUT_ERR_TOL``, or its p99 exceeds the baseline's
   by more than ``RDT_SERVE_ROLLOUT_P99_FACTOR``×. Both sides need
   ``RDT_SERVE_ROLLOUT_MIN_SAMPLES`` step-local samples first — a
   one-request blip must not kill a deploy. While the session is
   SHEDDING, judgment is suspended: saturation inflates both versions'
   windows, and rolling back a healthy canary for the pool's overload is
   the false positive this controller exists to not have.
4. **promote or roll back** — a ramp that reaches weight 1.0 healthy is
   promoted through the ordinary swap/retire machinery (the old primary
   drains, then unloads); the FIRST unhealthy verdict rolls back —
   weight→0, the canary group unloads, a typed ``rollout_rollback``
   event + flight-recorder blackbox bundle record why. Rollback is an
   OUTCOME, not an exception: ``run()`` returns a record either way, so
   a ``partial_fit`` loop shipping exports through ``rollout=`` keeps
   training past a bad epoch instead of dying on it.

A step that times out with NEITHER side reaching the min-sample floor
advances vacuously ("insufficient traffic" is no evidence of regression —
an idle session must still be able to deploy); an overall ``timeout``
rolls the whole rollout back. doc/serving.md "Guarded rollouts" documents
the state machine and the failure table rows.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from raydp_tpu import knobs, metrics
from raydp_tpu.log import get_logger
from raydp_tpu.serve.session import ServingError, ServingSession

logger = get_logger("serve.rollout")

__all__ = ["RolloutController"]


def _parse_ramp(spec: str) -> List[float]:
    steps = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        w = float(part)
        if not 0.0 < w <= 1.0:
            raise ValueError(
                f"RDT_SERVE_ROLLOUT_RAMP step {w!r} outside (0, 1]")
        steps.append(w)
    if not steps:
        raise ValueError("RDT_SERVE_ROLLOUT_RAMP is empty")
    if steps != sorted(steps):
        raise ValueError(
            f"RDT_SERVE_ROLLOUT_RAMP must be non-decreasing: {spec!r}")
    return steps


class RolloutController:
    """One guarded deployment of one export (see module docstring).
    Construct-and-``run()``; all knobs are re-read per rollout, so a
    ``partial_fit`` loop picks up retuned thresholds between epochs.

        ctl = RolloutController(srv, "/shared/model-v2", tag="epoch-3")
        outcome = ctl.run()
        outcome["outcome"]  # "promoted" | "rolled_back"

    ``steps`` / ``initial_weight`` / thresholds may be overridden per call
    (tests and the bench pin fast schedules); production uses the knobs."""

    def __init__(self, serving: ServingSession, export_dir: str,
                 tag: Optional[str] = None,
                 timeout: Optional[float] = None,
                 initial_weight: Optional[float] = None,
                 steps: Optional[List[float]] = None,
                 step_s: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 err_tol: Optional[float] = None,
                 p99_factor: Optional[float] = None):
        self.serving = serving
        self.export_dir = export_dir
        self.tag = tag
        self.timeout = timeout
        w0 = (float(knobs.get("RDT_SERVE_CANARY_WEIGHT"))
              if initial_weight is None else float(initial_weight))
        if not 0.0 < w0 <= 1.0:
            raise ValueError(f"canary weight {w0!r} outside (0, 1]")
        ramp = (steps if steps is not None
                else _parse_ramp(knobs.get("RDT_SERVE_ROLLOUT_RAMP")))
        # the schedule: the canary weight, then every ramp step above it,
        # ending at full weight — judged at every step boundary
        self.steps = [w0] + [w for w in ramp if w > w0]
        if self.steps[-1] < 1.0:
            self.steps.append(1.0)
        self.step_s = (float(knobs.get("RDT_SERVE_ROLLOUT_STEP_S"))
                       if step_s is None else float(step_s))
        self.min_samples = max(
            1, int(knobs.get("RDT_SERVE_ROLLOUT_MIN_SAMPLES"))
            if min_samples is None else int(min_samples))
        self.err_tol = (float(knobs.get("RDT_SERVE_ROLLOUT_ERR_TOL"))
                        if err_tol is None else float(err_tol))
        self.p99_factor = (float(knobs.get("RDT_SERVE_ROLLOUT_P99_FACTOR"))
                           if p99_factor is None else float(p99_factor))
        self.version: Optional[int] = None
        #: per-step judgment records, returned in the outcome (and shipped
        #: in the rollback blackbox bundle: the postmortem must show WHICH
        #: step failed on WHAT numbers)
        self.history: List[Dict[str, Any]] = []

    # ---- the judgment -------------------------------------------------------
    def _vrow(self, report: Dict[str, Any],
              version: int) -> Optional[Dict[str, Any]]:
        for row in report.get("versions", []):
            if row["version"] == version:
                return row
        return None

    def _judge(self, base0, canary0, base1, canary1,
               shedding: bool) -> Dict[str, Any]:
        """One judgment over the step-local deltas (cumulative counters at
        the step's start vs now). Returns ``verdict``:
        ``healthy`` / ``unhealthy`` / ``insufficient`` (window not full) /
        ``suspended`` (shedding gate active)."""
        out: Dict[str, Any] = {
            "canary_requests": canary1["requests"] - canary0["requests"],
            "canary_failed": canary1["failed"] - canary0["failed"],
            "base_requests": base1["requests"] - base0["requests"],
            "base_failed": base1["failed"] - base0["failed"],
            "canary_p99_ms": canary1["p99_ms"],
            "base_p99_ms": base1["p99_ms"],
        }
        if shedding:
            out["verdict"] = "suspended"
            return out
        c_n = out["canary_requests"] + out["canary_failed"]
        b_n = out["base_requests"] + out["base_failed"]
        if c_n < self.min_samples or b_n < self.min_samples:
            out["verdict"] = "insufficient"
            return out
        c_err = out["canary_failed"] / c_n
        b_err = out["base_failed"] / b_n
        out["canary_err_rate"] = round(c_err, 4)
        out["base_err_rate"] = round(b_err, 4)
        if c_err > b_err + self.err_tol:
            out["verdict"] = "unhealthy"
            out["reason"] = (
                f"error rate {c_err:.3f} exceeds baseline {b_err:.3f} "
                f"+ tolerance {self.err_tol}")
            return out
        # the latency arm needs its own sample floor: the p99 is read off
        # the per-version latency window, which only failed-free requests
        # feed, so a crash-looping canary must be caught by the error arm
        # above, not produce a spurious latency verdict off 3 samples
        if canary1["lat_n"] >= self.min_samples \
                and base1["lat_n"] >= self.min_samples \
                and base1["p99_ms"] > 0 \
                and canary1["p99_ms"] > self.p99_factor * base1["p99_ms"]:
            out["verdict"] = "unhealthy"
            out["reason"] = (
                f"p99 {canary1['p99_ms']:.1f}ms exceeds "
                f"{self.p99_factor}x baseline {base1['p99_ms']:.1f}ms")
            return out
        out["verdict"] = "healthy"
        return out

    # ---- the ramp -----------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        """Execute the rollout to its terminal state. Returns
        ``{"outcome": "promoted" | "rolled_back", "version", "export_dir",
        "tag", "steps": [...], "reason"?}``. Raises only on setup errors
        (the load itself failing, a closed session) — a judged rollback is
        a RETURN, not an exception."""
        srv = self.serving
        t0 = time.monotonic()
        metrics.inc("serve_rollouts_total")
        info = srv.load_version(self.export_dir, weight=self.steps[0],
                                tag=self.tag)
        self.version = v = info["version"]
        logger.info("rollout of %s started as v%d at weight %.3g "
                    "(ramp %s)", self.export_dir, v, self.steps[0],
                    self.steps)
        baseline = srv.serving_report()["servable"]["version"]
        for step_i, weight in enumerate(self.steps):
            if step_i > 0:
                srv.set_weight(v, weight)
            step_t0 = time.monotonic()
            poll = max(0.05, self.step_s / 20.0)
            rep0 = srv.serving_report()
            base0 = self._vrow(rep0, baseline)
            canary0 = self._vrow(rep0, v)
            if base0 is None or canary0 is None:
                return self._rollback("baseline or canary version vanished "
                                      "mid-ramp")
            verdict: Dict[str, Any] = {"verdict": "insufficient"}
            while True:
                time.sleep(poll)
                rep1 = srv.serving_report()
                base1 = self._vrow(rep1, baseline)
                canary1 = self._vrow(rep1, v)
                if canary1 is None:
                    return self._rollback("canary version vanished "
                                          "mid-ramp")
                if base1 is None:
                    # the baseline group disappeared under us (a concurrent
                    # hot_swap replaced the primary): the comparison frame
                    # is gone — fail safe, roll the canary back
                    return self._rollback(
                        f"baseline v{baseline} vanished mid-ramp "
                        "(concurrent swap?)")
                verdict = self._judge(base0, canary0, base1, canary1,
                                      rep1.get("shedding", False))
                self.history.append({"step": step_i, "weight": weight,
                                     **verdict})
                if verdict["verdict"] == "unhealthy":
                    return self._rollback(verdict.get("reason", "unhealthy"),
                                          verdict)
                if verdict["verdict"] == "healthy":
                    break  # step cleared: ramp on
                if self.timeout is not None \
                        and time.monotonic() - t0 >= self.timeout:
                    return self._rollback(
                        f"rollout exceeded timeout={self.timeout:.0f}s "
                        f"at step {step_i} (weight {weight})", verdict)
                if time.monotonic() - step_t0 >= self.step_s:
                    # the window never filled (or stayed suspended):
                    # insufficient traffic is no evidence of regression —
                    # advance, or an idle session could never deploy
                    logger.info(
                        "rollout v%d step %d (weight %.3g) advancing on "
                        "%s after %.1fs", v, step_i, weight,
                        verdict["verdict"], self.step_s)
                    break
        return self._promote()

    def _promote(self) -> Dict[str, Any]:
        v = self.version
        self.serving.promote_version(v)
        metrics.record_event("rollout_promote", session=self.serving.name,
                             version=v, export_dir=self.export_dir,
                             tag=self.tag or "", steps=len(self.history))
        logger.info("rollout v%d (%s) promoted to primary after %d "
                    "judgment(s)", v, self.export_dir, len(self.history))
        return {"outcome": "promoted", "version": v,
                "export_dir": self.export_dir, "tag": self.tag,
                "steps": self.history}

    def _rollback(self, reason: str,
                  verdict: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
        v = self.version
        srv = self.serving
        logger.error("rollout v%d (%s) ROLLING BACK: %s", v,
                     self.export_dir, reason)
        try:
            # weight first (stop NEW traffic this dispatcher step), then
            # drop (in-flight canary dispatches complete, replicas retire)
            srv.set_weight(v, 0.0)
            srv.drop_version(v)
        except ServingError:
            # already gone (session closing / concurrent drop): the
            # outcome below still records why we bailed
            logger.warning("rollout v%d rollback: version already gone", v)
        metrics.inc("serve_rollouts_rolled_back_total")
        metrics.record_event("rollout_rollback", session=srv.name,
                             version=v, export_dir=self.export_dir,
                             tag=self.tag or "", reason=reason[:300])
        # the postmortem bundle: which step died on what numbers, plus
        # every process's recent event ring (best-effort by contract)
        try:
            path = metrics.write_blackbox(
                f"rollout-{srv.name}",
                extra={"version": v, "export_dir": self.export_dir,
                       "tag": self.tag, "reason": reason,
                       "verdict": verdict, "steps": self.history})
            if path:
                logger.error("rollout rollback flight-recorder bundle "
                             "written to %s", path)
        except Exception:  # noqa: BLE001 - never mask the rollback itself
            logger.warning("rollout rollback blackbox harvest failed",
                           exc_info=True)
        return {"outcome": "rolled_back", "version": v,
                "export_dir": self.export_dir, "tag": self.tag,
                "reason": reason, "steps": self.history}
