"""Serving bundles: export a trained estimator, load it anywhere.

The RayDP reference's Estimator surface stops at ``fit``/``get_model``
(PAPER.md L5) — its users rebuild an inference loop by hand around the
returned model. A *servable* closes that gap: ``FlaxEstimator.export_serving``
/ ``KerasEstimator.export_serving`` write a self-contained directory

- ``servable.json`` — kind ("flax" | "keras") + format version,
- ``predict.pkl``  — the cloudpickled model object plus everything the
  estimator's own ``predict()`` used (column spec, preprocessor, cast
  policy, ``train=`` kwarg detection) and a shape/dtype template tree,
- ``ckpt/``        — the trained weights written through
  :mod:`raydp_tpu.train.checkpoint` (the same format ``fit`` checkpoints
  use, so a serving bundle restores with the exact machinery a resumed
  training run trusts),

and :func:`load_servable` rebuilds a :class:`Servable` in any process — the
driver for local smoke checks, or an executor actor as a serving replica
(:mod:`raydp_tpu.serve.replica`). Multi-host pools need ``export_dir`` on
shared storage, the same contract gang checkpoints already carry.

A Servable splits inference into the three phases the replica pipeline
overlaps (doc/serving.md): ``decode`` (Arrow → host arrays), ``place``
(host → device), ``apply`` (the jitted forward pass).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Tuple

import cloudpickle
import numpy as np
import pyarrow as pa

from raydp_tpu.log import get_logger
from raydp_tpu.train import checkpoint

logger = get_logger("serve.servable")

META_FILE = "servable.json"
BUNDLE_FILE = "predict.pkl"
CKPT_SUBDIR = "ckpt"
FORMAT_VERSION = 1


def _template_spec(state):
    """A shapes/dtypes-only twin of ``state`` — small enough to pickle into
    the bundle, rich enough for ``checkpoint.restore`` to rebuild host
    arrays into (its ``_host_template`` only reads ``.shape``/``.dtype``)."""
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            np.shape(x), getattr(x, "dtype", None) or np.asarray(x).dtype),
        state)


def _host_tree(state):
    """Host-side copy of the weight tree where one exists. A mesh-trained
    state whose shards span PROCESSES has no single-host value —
    ``np.asarray`` would throw — so such leaves pass through as global
    arrays: ``checkpoint.save`` routes them to the sharded multi-writer
    format (every process writes the shards it owns), and ``load_servable``
    reassembles via the same cross-topology restore a resumed gang uses.
    Single-process sharded arrays (any mesh shape) gather here as before."""
    import jax

    def _host(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return x
        return np.asarray(x)

    return jax.tree.map(_host, state)


def export_bundle(export_dir: str, kind: str, bundle: Dict[str, Any],
                  state) -> str:
    """Write a servable directory: meta + pickled bundle + the weight tree
    through ``checkpoint.save`` (step 0 — a bundle is a single immutable
    export, not a training timeline)."""
    os.makedirs(export_dir, exist_ok=True)
    bundle = dict(bundle)
    bundle["template"] = _template_spec(state)
    checkpoint.save(os.path.join(export_dir, CKPT_SUBDIR),
                    _host_tree(state), step=0)
    with open(os.path.join(export_dir, BUNDLE_FILE), "wb") as f:
        f.write(cloudpickle.dumps(bundle))
    meta = {"kind": kind, "format_version": FORMAT_VERSION}
    tmp = os.path.join(export_dir, f".{META_FILE}.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(meta, f)
    # meta lands last and atomically: its presence marks a complete bundle
    os.replace(tmp, os.path.join(export_dir, META_FILE))
    logger.info("exported %s servable to %s", kind, export_dir)
    return export_dir


class Servable:
    """A loaded model with the three-phase predict pipeline.

    ``predict_table`` chains the phases synchronously; the replica worker
    runs ``decode``+``place`` on a :class:`~raydp_tpu.data.feed
    .DevicePrefetcher` thread so batch ``k+1``'s staging and H2D overlap the
    jitted ``apply`` of batch ``k``."""

    def __init__(self, kind: str, columns: Dict[str, Tuple[Any, Any]],
                 apply_fn, nbytes: int):
        self.kind = kind
        #: feed-style column spec: name -> (column(s), dtype)
        self.columns = columns
        self._apply = apply_fn
        #: total weight bytes — the replica load report surfaces it
        self.nbytes = nbytes

    # -- decode ---------------------------------------------------------------
    def decode(self, table: pa.Table) -> Dict[str, np.ndarray]:
        """Arrow → the host batch dict the jitted apply consumes. Spec
        entries whose column(s) the table lacks wholesale (the label a
        serving request never carries) synthesize as zeros, exactly like
        ``FlaxEstimator.predict``; a partially-missing entry is a schema
        mismatch and fails loudly."""
        from raydp_tpu.data.feed import _as_numpy

        have = set(table.schema.names)
        batch: Dict[str, np.ndarray] = {}
        for name, (cspec, dt) in self.columns.items():
            cnames = (cspec,) if isinstance(cspec, str) else tuple(cspec)
            missing = [c for c in cnames if c not in have]
            if missing and len(missing) < len(cnames):
                raise ValueError(
                    f"servable spec entry {name!r} is partially missing from "
                    f"the request schema: missing {missing}")
            if missing:
                shape = ((table.num_rows,) if len(cnames) == 1
                         else (table.num_rows, len(cnames)))
                batch[name] = np.zeros(shape, np.dtype(dt))
            else:
                batch[name] = _as_numpy(table, list(cnames), dt)
        return batch

    # -- place ----------------------------------------------------------------
    @staticmethod
    def place(batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Host batch → device arrays (the H2D phase)."""
        import jax

        return {k: jax.device_put(v) for k, v in batch.items()}

    # -- apply ----------------------------------------------------------------
    def apply(self, placed: Dict[str, Any]) -> np.ndarray:
        """The jitted forward pass; returns float32 host predictions, one
        row per input row."""
        return np.asarray(self._apply(placed))

    def predict_table(self, table: pa.Table) -> np.ndarray:
        return self.apply(self.place(self.decode(table)))


def _restore_state(export_dir: str, template):
    restored = checkpoint.restore(os.path.join(export_dir, CKPT_SUBDIR),
                                  template)
    if restored is None:
        raise FileNotFoundError(
            f"servable at {export_dir!r} has no complete checkpoint under "
            f"{CKPT_SUBDIR}/")
    return restored[0]


def _tree_nbytes(state) -> int:
    import jax

    return sum(int(np.asarray(x).nbytes)
               for x in jax.tree.leaves(state))


def _build_flax(bundle: Dict[str, Any], state) -> Servable:
    import jax
    import jax.numpy as jnp

    from raydp_tpu.train.flax_estimator import _cast_floating

    model = bundle["model"]
    preprocessor = bundle.get("preprocessor")
    custom = bool(bundle.get("custom"))
    compute_dtype = bundle.get("compute_dtype")
    kwargs = {"train": False} if bundle.get("takes_train") else {}
    variables = state

    @jax.jit
    def infer(jbatch):
        if custom:
            inputs = (preprocessor(jbatch)[0] if preprocessor is not None
                      else jbatch["features"])
        else:
            inputs = jbatch["features"]
        inputs = _cast_floating(inputs, compute_dtype)
        preds = model.apply(variables, inputs, **kwargs)
        if preds.ndim >= 2 and preds.shape[-1] == 1:
            preds = preds.squeeze(-1)
        return preds.astype(jnp.float32)

    return Servable("flax", bundle["columns"], infer, _tree_nbytes(state))


def _build_keras(bundle: Dict[str, Any], state) -> Servable:
    import jax
    import jax.numpy as jnp

    model = bundle["model"]
    tv = [jnp.asarray(v) for v in state["tv"]]
    ntv = [jnp.asarray(v) for v in state["ntv"]]

    @jax.jit
    def infer(jbatch):
        preds, _ = model.stateless_call(tv, ntv, jbatch["features"],
                                        training=False)
        if preds.ndim >= 2 and preds.shape[-1] == 1:
            preds = preds.squeeze(-1)
        return preds.astype(jnp.float32)

    return Servable("keras", bundle["columns"], infer, _tree_nbytes(state))


_BUILDERS = {"flax": _build_flax, "keras": _build_keras}


def load_servable(export_dir: str) -> Servable:
    """Rebuild a :class:`Servable` from an exported directory (weights
    restored through ``train/checkpoint.py``, like any training resume)."""
    meta_path = os.path.join(export_dir, META_FILE)
    if not os.path.exists(meta_path):
        raise FileNotFoundError(
            f"no servable at {export_dir!r} ({META_FILE} missing — was "
            "export_serving() called, and is the path visible on this "
            "machine?)")
    with open(meta_path, "r", encoding="utf-8") as f:
        meta = json.load(f)
    kind = meta.get("kind")
    builder = _BUILDERS.get(kind)
    if builder is None:
        raise ValueError(f"unknown servable kind {kind!r} in {export_dir!r}")
    with open(os.path.join(export_dir, BUNDLE_FILE), "rb") as f:
        bundle = cloudpickle.loads(f.read())
    state = _restore_state(export_dir, bundle["template"])
    return builder(bundle, state)
