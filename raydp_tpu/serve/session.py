"""Driver-side serving sessions: micro-batched, hedged inference over the
executor pool.

:class:`ServingSession` loads an exported servable
(``estimator.export_serving(dir)``) onto N executor-resident replicas and
exposes a thread-safe ``predict(batch)`` / ``predict_async(rows)`` API over
the existing actor RPC plane. Mechanisms, each reusing an ETL-plane design:

- **dynamic micro-batching** — concurrent requests coalesce into one device
  dispatch up to ``RDT_SERVE_MAX_BATCH`` rows or an
  ``RDT_SERVE_BATCH_TIMEOUT_MS`` latency budget; the batched output demuxes
  back per request. The replica side stages decode/H2D for the next batch
  on a ``DevicePrefetcher`` thread while the jitted apply runs (PR 1).
- **replica routing + hedged requests** — dispatches land on the
  least-busy replica (per-replica in-flight counters, ties rotating — the
  PR 5 scheduler's shape); a dispatch older than
  ``max(RDT_SERVE_HEDGE_MULTIPLIER × latency-quantile,
  RDT_SERVE_HEDGE_MIN_MS)`` is hedged onto a second replica, first
  responder wins, the loser's result is discarded and counted (PR 5's
  speculation, re-aimed at tail latency).
- **fault path** — a replica that dies mid-request (connection lost, or a
  restarted executor answering ``ReplicaNotLoaded``) re-routes the dispatch
  through the same hedge machinery instead of surfacing an error; the
  replica reloads in the background and rejoins the rotation. Requests fail
  only when every replica has refused within the re-route grace.
- **observability** — per-replica request/batch/row counters, batch
  occupancy and queue-depth gauges, and request p50/p99 in
  :meth:`serving_report` (the ``shuffle_stage_report`` twin), plus
  ``serve:batch`` / ``serve:hedge`` trace spans.

All routing/hedging/demux state is owned by ONE dispatcher thread fed by an
event queue — RPC completion callbacks (which run on client read-loop
threads) only enqueue, so no lock ordering exists to get wrong and the
read loops never block.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from raydp_tpu import knobs, metrics, profiler
from raydp_tpu.log import get_logger
from raydp_tpu.runtime.rpc import ConnectionLost, RemoteError

logger = get_logger("serve.session")

#: completed-batch latencies required before the hedge deadline is trusted
#: (below this the quantile is noise and hedging would fire on warmup jitter)
_HEDGE_MIN_SAMPLES = 8
#: bounded latency reservoirs (batch + request) for the quantile/report
_LAT_WINDOW = 2048


class ServingError(RuntimeError):
    """A request failed on every live replica within the re-route grace."""


class ServingOverloaded(ServingError):
    """A request was shed at admission: the session's outstanding queue
    (accepted, unfinished requests) is at ``RDT_SERVE_MAX_QUEUE``. Typed
    and RETRIABLE by contract — unlike :class:`ServingError` this is not a
    verdict on the request, only on the moment: the queue drains as
    batches complete, so back off and retry (or route elsewhere)."""


#: ``RemoteError.exc_type`` values that mark a replica/infrastructure
#: failure worth re-routing: a restarted executor's empty registry, and the
#: chaos plane's transient ``raise`` (doc/serving.md failure table). Any
#: other remote exception is a deterministic application error — replaying
#: it on another replica replays the error, so it fails fast instead.
_REROUTE_EXC_TYPES = ("ReplicaNotLoaded", "InjectedFault")


def _reroutable(err: BaseException) -> bool:
    if isinstance(err, (ConnectionLost, OSError)):
        return True
    return isinstance(err, RemoteError) \
        and err.exc_type in _REROUTE_EXC_TYPES


def _as_table(data) -> pa.Table:
    if isinstance(data, pa.Table):
        return data
    if isinstance(data, dict):
        return pa.table({k: np.asarray(v) for k, v in data.items()})
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:  # pragma: no cover - pandas is a hard dep elsewhere
        pass
    raise TypeError(f"cannot serve rows of type {type(data)}; pass a "
                    "pyarrow Table, pandas DataFrame, or dict of arrays")


def _encode(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def _quantile(sample: Sequence[float], q: float) -> float:
    s = sorted(sample)
    if not s:
        return 0.0
    return s[min(len(s) - 1, int(q * len(s)))]


class _Request:
    __slots__ = ("table", "fut", "t_enq", "rows", "span")

    def __init__(self, table: pa.Table, fut: Future):
        self.table = table
        self.fut = fut
        self.t_enq = time.monotonic()
        self.rows = table.num_rows
        # the request's serve:predict span opens on the caller's thread
        # (joining the caller's trace, or minting one) and closes when the
        # demuxed result lands; its context is what the dispatcher
        # activates around the batch submit, so serve:batch / serve:hedge /
        # replica serve:apply all parent here
        self.span = profiler.open_span("serve:predict", "serve",
                                       rows=self.rows)

    @property
    def ctx(self):
        return profiler.span_context(self.span)

    def finish(self, **args) -> None:
        profiler.close_span(self.span, **args)


class _Attempt:
    __slots__ = ("replica", "t0", "hedge")

    def __init__(self, replica: "_ReplicaState", t0: float, hedge: bool):
        self.replica = replica
        self.t0 = t0
        self.hedge = hedge


class _Dispatch:
    """One coalesced batch in flight (possibly on two replicas at once)."""

    __slots__ = ("id", "payload", "rows", "parts", "attempts", "tried",
                 "hedged", "done", "t_first", "last_error")

    def __init__(self, did: int, payload: bytes, rows: int, parts):
        self.id = did
        self.payload = payload
        self.rows = rows
        self.parts = parts            # [(request, row offset)]
        self.attempts: Dict[int, _Attempt] = {}
        self.tried: set = set()       # replica ids an attempt ran on
        self.hedged = False
        self.done = False
        self.t_first = time.monotonic()
        self.last_error: Optional[BaseException] = None


class _ReplicaState:
    """Driver-side view of one replica: its actor handle, its in-flight
    count, and its readiness (False while the executor restarts/reloads)."""

    def __init__(self, rid: str, replica, executor_name: str):
        self.rid = rid
        #: the ActorHandle — named `replica` so rdtlint's rpc-surface rule
        #: resolves `replica.submit("serve_predict", ...)` call sites against
        #: the actor surface (tools/rdtlint/config.py RPC_RECEIVER_SURFACES)
        self.replica = replica
        self.executor = executor_name
        self.inflight = 0
        self.inflight_peak = 0
        self.ready = True
        self.reloading = False
        # counters for serving_report()
        self.requests = 0
        self.batches = 0
        self.rows = 0
        self.hedges = 0
        self.reloads = 0


class ServingSession:
    """See module docstring. Construct with a live ETL session (or an
    explicit executor-handle list) and a servable ``export_dir``:

        est.fit_on_frame(train_df)
        est.export_serving("/shared/model-v1")
        srv = ServingSession("/shared/model-v1", session=session)
        preds = srv.predict(rows)          # or predict_async(rows) -> Future
        srv.serving_report(); srv.close()

    Knobs (all re-read at construction; doc/serving.md): batching
    ``RDT_SERVE_MAX_BATCH`` / ``RDT_SERVE_BATCH_TIMEOUT_MS``, routing
    ``RDT_SERVE_MAX_INFLIGHT``, hedging ``RDT_SERVE_HEDGE`` /
    ``RDT_SERVE_HEDGE_QUANTILE`` / ``RDT_SERVE_HEDGE_MULTIPLIER`` /
    ``RDT_SERVE_HEDGE_MIN_MS``, fault path ``RDT_SERVE_REROUTE_GRACE_S``,
    overload shedding ``RDT_SERVE_MAX_QUEUE``, replica staging
    ``RDT_SERVE_PREFETCH``."""

    def __init__(self, export_dir: str, session=None,
                 executors: Optional[List] = None,
                 num_replicas: Optional[int] = None,
                 name: str = "serving"):
        if executors is None:
            if session is None:
                from raydp_tpu.context import active_session
                session = active_session()
            if session is None:
                raise ValueError("pass session= or executors= (no active "
                                 "raydp_tpu session to serve from)")
            executors = list(session.executors)
        if not executors:
            raise ValueError("serving needs at least one executor")
        #: the live-member view replica reloads route through: when the
        #: executor hosting a replica is RETIRED from the pool (not merely
        #: restarting), the background reload re-binds the replica onto a
        #: surviving member instead of probing the corpse until the
        #: re-route grace expires. None with an explicit executors= list
        #: (no pool to consult — reloads then probe the fixed handle only).
        self._session = session
        if num_replicas is not None:
            if num_replicas < 1:
                raise ValueError("num_replicas must be >= 1")
            executors = [executors[i % len(executors)]
                         for i in range(num_replicas)]
        self.export_dir = export_dir
        self.name = name
        self._max_batch = max(1, int(knobs.get("RDT_SERVE_MAX_BATCH")))
        self._timeout_s = max(
            0.0, float(knobs.get("RDT_SERVE_BATCH_TIMEOUT_MS")) / 1000.0)
        self._max_inflight = max(1, int(knobs.get("RDT_SERVE_MAX_INFLIGHT")))
        self._hedge_on = bool(knobs.get("RDT_SERVE_HEDGE"))
        self._hedge_q = float(knobs.get("RDT_SERVE_HEDGE_QUANTILE"))
        self._hedge_mult = float(knobs.get("RDT_SERVE_HEDGE_MULTIPLIER"))
        self._hedge_min_s = max(
            0.0, float(knobs.get("RDT_SERVE_HEDGE_MIN_MS")) / 1000.0)
        self._reroute_grace_s = float(knobs.get("RDT_SERVE_REROUTE_GRACE_S"))
        self._max_queue = max(0, int(knobs.get("RDT_SERVE_MAX_QUEUE")))
        # overload shedding state — touched from REQUEST threads (admission
        # in predict_async, decrements from future callbacks), never by the
        # dispatcher alone, so unlike the dispatcher-owned state below it
        # needs its own lock
        self._adm_lock = threading.Lock()
        self._outstanding = 0  # guarded-by: _adm_lock
        self._shed_count = 0   # guarded-by: _adm_lock
        #: serializes hot_swap() callers (the swap itself applies on the
        #: dispatcher thread; this only orders concurrent swap requests)
        self._swap_lock = threading.Lock()
        self._swap_drain_s = max(
            0.0, float(knobs.get("RDT_SERVE_SWAP_DRAIN_S")))

        self._replicas: List[_ReplicaState] = []
        loads = []
        for i, h in enumerate(executors):
            rid = f"{name}-r{i}"
            rep = _ReplicaState(rid, h, getattr(h, "name", None) or f"ex{i}")
            # parallel load: each replica pays its jax import + jit once,
            # concurrently, instead of serializing session bring-up
            replica = rep.replica
            loads.append(replica.submit("serve_load", rid, export_dir))
            self._replicas.append(rep)
        for f in loads:
            f.result(timeout=180.0)

        # dispatcher-owned state (no locks: one thread mutates it)
        self._events: "queue.Queue" = queue.Queue()
        self._pending: List[_Request] = []     # awaiting coalescing
        self._pending_rows = 0
        self._inflight: Dict[int, _Dispatch] = {}
        self._parked: List[_Dispatch] = []     # waiting for a replica
        self._rr = itertools.count()
        self._did = itertools.count()
        # servable-version state (dispatcher-owned after construction; the
        # active version answers every new dispatch, retiring versions only
        # finish what they already hold)
        self._version = 1
        self._active_tag: Optional[str] = None
        self._swaps = 0
        #: (drain deadline, replicas, version) of swapped-out servables
        self._retiring: List = []
        self._closed = False
        self._batch_lat: List[float] = []      # bounded; hedge quantile base
        self._req_lat: List[float] = []        # bounded; report p50/p99
        self._occupancy: List[int] = []        # rows per dispatched batch
        self._queue_depth_peak = 0
        self._stats = {"requests": 0, "batches": 0, "rows": 0,
                       "hedged": 0, "hedge_won": 0, "hedge_lost": 0,
                       "rerouted": 0, "failed": 0}
        self._dispatcher = threading.Thread(
            target=self._run, daemon=True, name=f"rdt-serve-dispatch-{name}")
        self._dispatcher.start()

    # ---- public API ---------------------------------------------------------
    def predict_async(self, rows) -> Future:
        """Enqueue rows (Table / DataFrame / dict of arrays); the Future
        resolves to a float32 prediction array, one entry per input row.
        Thread-safe; callable from any number of request threads.

        Overload shedding: past ``RDT_SERVE_MAX_QUEUE`` outstanding
        (accepted, unfinished) requests this fails fast with the typed
        retriable :class:`ServingOverloaded` instead of growing the
        dispatcher queue without bound — a burst degrades to rejections,
        never to a collapsing dispatcher (doc/serving.md "Overload")."""
        table = _as_table(rows)
        fut: Future = Future()
        if table.num_rows == 0:
            fut.set_result(np.empty((0,), np.float32))
            return fut
        if self._closed:
            raise ServingError("serving session is closed")
        with self._adm_lock:
            if self._max_queue > 0 and self._outstanding >= self._max_queue:
                self._shed_count += 1
                outstanding = self._outstanding
                shed = True
            else:
                self._outstanding += 1
                shed = False
        if shed:
            metrics.inc("serve_shed_total")
            metrics.record_event("overload_shed", session=self.name,
                                 outstanding=outstanding,
                                 max_queue=self._max_queue)
            raise ServingOverloaded(
                f"serving session {self.name!r} is saturated "
                f"({outstanding} outstanding requests >= "
                f"RDT_SERVE_MAX_QUEUE={self._max_queue}); retry with "
                "backoff")
        # whichever way the request ends (demuxed result, re-route
        # exhaustion, close) the admission slot releases with its future
        fut.add_done_callback(self._release_admission)
        self._events.put(("req", _Request(table, fut)))
        if self._closed and not fut.done():
            # close() raced the enqueue: the request may sit behind the
            # stop event on a queue nobody drains anymore — fail it here
            # rather than leave a Future that never resolves (the winner
            # path guards set_result with done(), so the benign double
            # race resolves to whichever side got there first)
            try:
                fut.set_exception(ServingError("serving session is closed"))
            except Exception:  # noqa: BLE001 - lost the race: it completed
                pass
        return fut

    def predict(self, rows, timeout: Optional[float] = None) -> np.ndarray:
        """Synchronous :meth:`predict_async`."""
        return self.predict_async(rows).result(timeout=timeout)

    def _release_admission(self, _fut) -> None:
        with self._adm_lock:
            self._outstanding = max(0, self._outstanding - 1)

    def _shedding(self) -> bool:
        """Saturated right now? While True the dispatcher suppresses
        hedging — a hedge is a duplicate dispatch, and duplicating work
        while shedding new requests amplifies exactly the overload the
        shed exists to absorb."""
        with self._adm_lock:
            return self._max_queue > 0 \
                and self._outstanding >= self._max_queue

    def hot_swap(self, export_dir: str, tag: Optional[str] = None,
                 timeout: float = 180.0) -> Dict[str, Any]:
        """Atomically roll the session onto a new servable under live
        traffic: load the bundle at ``export_dir`` BESIDE the active one on
        every replica's executor (distinct replica ids — the registry holds
        both), shift all new dispatches to it in one dispatcher step, and
        retire the old version in the background once its in-flight work
        drains (bounded by ``RDT_SERVE_SWAP_DRAIN_S``; stragglers still
        complete, the registry entry just goes away). No request is dropped:
        every response comes from exactly one version — the one its
        dispatch was routed to. ``tag`` annotates the version in
        :meth:`serving_report` (``partial_fit`` passes the source epoch).
        Thread-safe; concurrent swaps serialize in call order."""
        if self._closed:
            raise ServingError("serving session is closed")
        with self._swap_lock:
            # replica handles/executors are dispatcher-owned state (reloads
            # re-bind them): snapshot them ON the dispatcher thread instead
            # of racing _maybe_rebind from here
            snap: Future = Future()
            self._events.put(("swap_prep", snap))
            members = snap.result(timeout=30.0)
            v = self._version + 1
            new_reps: List[_ReplicaState] = []
            loads = []
            for i, (handle, executor) in enumerate(members):
                rid = f"{self.name}-v{v}-r{i}"
                rep = _ReplicaState(rid, handle, executor)
                # parallel load beside the active servable — the old rid
                # keeps serving while the new one pays its jit
                replica = rep.replica
                loads.append(replica.submit("serve_load", rid, export_dir))
                new_reps.append(rep)
            errors = []
            for f in loads:
                try:
                    f.result(timeout=timeout)
                except Exception as e:  # noqa: BLE001 - collected below
                    errors.append(e)
            if errors:
                # never leave a half-loaded version pinning executor RAM:
                # unload whatever DID land, then surface the failure
                self._unload_replicas(new_reps, v)
                raise ServingError(
                    f"hot swap to {export_dir!r} failed loading "
                    f"{len(errors)}/{len(loads)} replica(s); the partial "
                    f"load was rolled back") from errors[0]
            done: Future = Future()
            self._events.put(("swap", new_reps, export_dir, v, tag, done))
            return done.result(timeout=30.0)

    def serving_report(self) -> Dict[str, Any]:
        """Counters + latency snapshot (the ``shuffle_stage_report`` twin
        for the serving plane; columns documented in doc/serving.md)."""
        if self._closed and not self._dispatcher.is_alive():
            return self._report()  # post-close snapshot: nothing mutates
        done: Future = Future()
        self._events.put(("report", done))
        return done.result(timeout=30.0)

    def close(self, unload: bool = True) -> None:
        """Stop the dispatcher; in-flight work is failed, replicas unloaded
        (``unload=False`` keeps them for a successor session)."""
        if self._closed:
            return
        self._closed = True
        self._events.put(("stop",))
        self._dispatcher.join(timeout=30.0)
        if unload:
            # the active replicas plus any swapped-out version still
            # draining (the dispatcher is down: nothing retires them now)
            doomed = list(self._replicas)
            for _, reps, _ in self._retiring:
                doomed.extend(reps)
            self._retiring = []
            for rep in doomed:
                try:
                    rep.replica.call("serve_unload", rep.rid, timeout=10.0)
                except Exception:  # noqa: BLE001 - executor may be gone
                    pass

    def __enter__(self) -> "ServingSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- dispatcher internals (single thread) -------------------------------
    def _run(self) -> None:
        while True:
            timeout = self._next_wakeup()
            try:
                ev = self._events.get(timeout=timeout)
            except queue.Empty:
                ev = None
            try:
                if ev is not None:
                    kind = ev[0]
                    if kind == "stop":
                        self._drain_stop()
                        return
                    if kind == "req":
                        self._on_request(ev[1])
                    elif kind == "done":
                        self._on_done(ev[1], ev[2], ev[3], ev[4])
                    elif kind == "replica_up":
                        self._on_replica_up(ev[1], ev[2])
                    elif kind == "swap_prep":
                        # a torn mid-rebind (handle, name) pair is what the
                        # dispatcher-thread copy exists to prevent
                        ev[1].set_result([(r.replica, r.executor)
                                          for r in self._replicas])
                    elif kind == "swap":
                        self._on_swap(ev[1], ev[2], ev[3], ev[4], ev[5])
                    elif kind == "report":
                        ev[1].set_result(self._report())
                self._flush_batches()
                self._maybe_hedge()
                self._retry_parked()
                self._retire_swapped()
                # refresh on every loop pass (arrivals, flushes, drains
                # alike) so an idle session reads 0, not the last
                # pre-dispatch depth; labeled per session so two sessions
                # in one driver never overwrite each other's slot
                metrics.set_gauge("serve_queue_depth",
                                  len(self._pending) + len(self._inflight),
                                  label=self.name)
            except Exception:  # noqa: BLE001 - the loop must survive anything
                # a dead dispatcher bricks every current and future request;
                # per-batch/per-dispatch errors are already routed to their
                # own futures, so whatever reaches here is a bug to log,
                # never a reason to stop serving
                logger.exception("serving dispatcher error (loop continues)")

    def _next_wakeup(self) -> Optional[float]:
        """Sleep until the earliest deadline the loop owns: the oldest
        pending batch's flush, or the next hedge-eligibility instant."""
        deadlines = []
        if self._pending:
            deadlines.append(self._pending[0].t_enq + self._timeout_s)
        hedge_after = self._hedge_deadline()
        if hedge_after is not None:
            for d in self._inflight.values():
                if not d.hedged and not d.done:
                    deadlines.append(d.t_first + hedge_after)
        if self._parked:
            deadlines.append(time.monotonic() + 0.05)
        if self._retiring:
            deadlines.append(time.monotonic() + 0.05)
        if not deadlines:
            return None
        return max(0.0, min(deadlines) - time.monotonic()) or 0.001

    # -- batching -------------------------------------------------------------
    def _on_request(self, req: _Request) -> None:
        self._stats["requests"] += 1
        metrics.inc("serve_requests_total")
        self._pending.append(req)
        self._pending_rows += req.rows
        self._queue_depth_peak = max(
            self._queue_depth_peak, len(self._pending) + len(self._inflight))

    def _flush_batches(self) -> None:
        while self._pending:
            full = self._pending_rows >= self._max_batch
            aged = (time.monotonic() - self._pending[0].t_enq
                    >= self._timeout_s)
            if not (full or aged):
                return
            # coalesce only schema-equal requests: a mixed batch would fail
            # pa.concat_tables and punish the well-formed requests packed
            # with it; the other-schema requests stay pending and form
            # their own batch on a later pass of this loop
            schema = self._pending[0].table.schema
            batch: List[_Request] = []
            rows = 0
            rest: List[_Request] = []
            for r in self._pending:
                if (batch and rows + r.rows > self._max_batch) \
                        or not r.table.schema.equals(schema):
                    rest.append(r)
                    continue
                batch.append(r)
                rows += r.rows
            self._pending = rest
            self._pending_rows -= rows
            self._dispatch_new(batch, rows)

    def _dispatch_new(self, batch: List[_Request], rows: int) -> None:
        parts, off = [], 0
        for r in batch:
            parts.append((r, off))
            off += r.rows
        try:
            table = (batch[0].table if len(batch) == 1
                     else pa.concat_tables([r.table for r in batch]))
            payload = _encode(table)
        except Exception as e:  # noqa: BLE001 - a bad request fails fast
            self._stats["failed"] += len(batch)
            for r in batch:
                if not r.fut.done():
                    r.fut.set_exception(e)
            return
        d = _Dispatch(next(self._did), payload, rows, parts)
        self._stats["batches"] += 1
        self._stats["rows"] += rows
        metrics.inc("serve_batches_total")
        metrics.inc("serve_rows_total", rows)
        metrics.observe("serve_batch_occupancy_rows", rows)
        self._occupancy.append(rows)
        if len(self._occupancy) > _LAT_WINDOW:
            del self._occupancy[:-_LAT_WINDOW]
        self._submit(d, hedge=False)

    # -- routing --------------------------------------------------------------
    def _choose(self, d: _Dispatch) -> Optional[_ReplicaState]:
        """Least-busy ready replica not already carrying this dispatch,
        round-robin on ties, respecting the per-replica in-flight cap —
        except when EVERY ready replica is at cap, where the least-busy one
        is taken anyway (a serving request must queue, not park forever)."""
        start = next(self._rr)
        k = len(self._replicas)
        best = None
        for allow_full in (False, True):
            for i in range(k):
                rep = self._replicas[(start + i) % k]
                if not rep.ready or rep.rid in d.tried:
                    continue
                if not allow_full and rep.inflight >= self._max_inflight:
                    continue
                if best is None or rep.inflight < best.inflight:
                    best = rep
            if best is not None:
                return best
        return None

    def _submit(self, d: _Dispatch, hedge: bool) -> bool:
        """Route and send one attempt; True only when an attempt is
        actually in flight (the hedge accounting keys on it)."""
        rep = self._choose(d)
        if rep is None:
            if hedge:
                return False  # no second replica free: simply do not hedge
            self._park(d)
            return False
        d.tried.add(rep.rid)
        t0 = time.monotonic()
        span = "serve:hedge" if hedge else "serve:batch"
        try:
            # the span covers the driver-side submit (encode happened at
            # coalesce time); the replica-side serve:apply span carries the
            # device half of the timeline. The batch joins the FIRST
            # coalesced request's trace (a batch has one parent lane; the
            # sibling requests' spans still record their own latency), so
            # the RPC layer ships serve:batch as the remote apply's parent
            with profiler.activate(d.parts[0][0].ctx if d.parts else None):
                with profiler.trace(span, "serve", replica=rep.rid,
                                    rows=d.rows, requests=len(d.parts)):
                    replica = rep.replica
                    fut = replica.submit("serve_predict", rep.rid, d.payload)
        except (ConnectionLost, OSError) as e:
            # the executor is unreachable (restarting): take the replica out
            # of rotation, start its background reload, and re-route
            self._note_replica_error(_Attempt(rep, t0, hedge), e)
            self._attempt_failed(d, rep, e)
            return False
        rep.inflight += 1
        rep.inflight_peak = max(rep.inflight_peak, rep.inflight)
        rep.batches += 1
        rep.requests += len(d.parts)
        rep.rows += d.rows
        if hedge:
            rep.hedges += 1
        aid = id(fut)
        d.attempts[aid] = _Attempt(rep, t0, hedge)
        self._inflight[d.id] = d

        def _cb(f, did=d.id, aid=aid, rid=rep.rid):
            # client read-loop thread: enqueue only, never block
            self._events.put(("done", did, aid, rid, f))

        fut.add_done_callback(_cb)
        return True

    def _park(self, d: _Dispatch) -> None:
        """No routable replica right now (all restarting/reloading): hold
        the dispatch and retry as replicas come back, up to the grace."""
        if time.monotonic() - d.t_first > self._reroute_grace_s:
            self._fail_dispatch(d)
            return
        if d not in self._parked:
            # a parked dispatch may be re-tried on any replica again once
            # one reloads — a reloaded replica is a FRESH process
            d.tried.clear()
            self._parked.append(d)
        # parked work is the strongest signal a dead replica is still
        # needed: re-kick any reload that previously gave up, so a
        # transient full outage longer than one reload pass does not brick
        # the session for its remaining lifetime
        for rep in self._replicas:
            if not rep.ready and not rep.reloading:
                rep.reloading = True
                threading.Thread(target=self._reload, args=(rep,),
                                 daemon=True,
                                 name=f"rdt-serve-reload-{rep.rid}").start()

    def _retry_parked(self) -> None:
        if not self._parked:
            return
        parked, self._parked = self._parked, []
        for d in parked:
            if not d.done:
                self._submit(d, hedge=False)

    # -- completion / hedging / fault path ------------------------------------
    def _on_done(self, did: int, aid: int, rid: str, fut: Future) -> None:
        d = self._inflight.get(did)
        if d is None:
            return
        att = d.attempts.pop(aid, None)
        if att is not None:
            att.replica.inflight = max(0, att.replica.inflight - 1)
        err = fut.exception()
        if d.done:
            # the loser of a won hedge (or of a rescue): discard, count
            if err is None and att is not None:
                self._stats["hedge_lost"] += 1
                metrics.inc("serve_hedge_lost_total")
            if not d.attempts:
                self._inflight.pop(did, None)
            if err is not None:
                self._note_replica_error(att, err)
            return
        if err is None:
            d.done = True
            if att is not None and att.hedge:
                self._stats["hedge_won"] += 1
                metrics.inc("serve_hedge_won_total")
            now = time.monotonic()
            if att is not None:
                self._batch_lat.append(now - att.t0)
                if len(self._batch_lat) > _LAT_WINDOW:
                    del self._batch_lat[:-_LAT_WINDOW]
            preds = np.asarray(fut.result())
            for req, off in d.parts:
                if not req.fut.done():  # close()/race-failed futures skip
                    req.fut.set_result(preds[off:off + req.rows])
                self._req_lat.append(now - req.t_enq)
                metrics.observe("serve_request_seconds", now - req.t_enq)
                req.finish(replica=rid)
            if len(self._req_lat) > _LAT_WINDOW:
                del self._req_lat[:-_LAT_WINDOW]
            if not d.attempts:
                self._inflight.pop(did, None)
            return
        # failed attempt
        self._note_replica_error(att, err)
        self._attempt_failed(d, att.replica if att else None, err)

    def _attempt_failed(self, d: _Dispatch, rep: Optional[_ReplicaState],
                        err: BaseException) -> None:
        d.last_error = err
        if d.attempts:
            return  # a sibling copy is still racing; it may still win
        if not _reroutable(err):
            # deterministic application error (bad schema, model bug):
            # another replica would compute the same failure — fail the
            # request now instead of burning the re-route grace on it
            self._fail_dispatch(d)
            return
        if time.monotonic() - d.t_first > self._reroute_grace_s:
            self._fail_dispatch(d)
            return
        self._stats["rerouted"] += 1
        metrics.inc("serve_rerouted_total")
        logger.warning("serve dispatch %d re-routing off %s after: %s",
                       d.id, rep.rid if rep else "?", err)
        self._submit(d, hedge=False)

    def _fail_dispatch(self, d: _Dispatch) -> None:
        d.done = True
        self._inflight.pop(d.id, None)
        self._stats["failed"] += len(d.parts)
        metrics.inc("serve_failed_total", len(d.parts))
        err = ServingError(
            f"request failed on every replica within "
            f"{self._reroute_grace_s:.0f}s (last error: {d.last_error})")
        err.__cause__ = d.last_error
        for req, _ in d.parts:
            if not req.fut.done():
                req.fut.set_exception(err)
            req.finish(failed=True)
        metrics.record_event("request_failed", dispatch=d.id,
                             requests=len(d.parts),
                             last_error=str(d.last_error)[:300])
        # the ServingError postmortem bundle (doc/observability.md) — on a
        # BACKGROUND thread: the harvest RPCs every live process with a 10s
        # timeout each, and this runs on the dispatcher event loop, which
        # must keep batching/hedging/demuxing the session's OTHER requests
        # (a hung executor is exactly the scenario that got us here).
        # Capped per label inside write_blackbox, best-effort by contract.
        threading.Thread(target=self._write_blackbox_bg, args=(err,),
                         daemon=True,
                         name=f"rdt-serve-blackbox-{self.name}").start()

    def _write_blackbox_bg(self, err: BaseException) -> None:
        try:
            path = metrics.write_blackbox(f"serve-{self.name}", err)
            if path:
                logger.warning("serve request failed on every replica; "
                               "flight-recorder bundle written to %s", path)
        except Exception:  # noqa: BLE001 - never mask the request failure
            logger.warning("blackbox harvest for failed serve dispatch "
                           "failed", exc_info=True)

    def _note_replica_error(self, att: Optional[_Attempt],
                            err: BaseException) -> None:
        """Infra errors take the replica out of rotation and start a
        background reload; app errors (a bad request) leave it serving."""
        if att is None:
            return
        rep = att.replica
        not_loaded = (isinstance(err, RemoteError)
                      and err.exc_type == "ReplicaNotLoaded")
        if not (isinstance(err, ConnectionLost) or not_loaded):
            return
        if rep.reloading:
            return
        rep.ready = False
        rep.reloading = True
        metrics.record_event("replica_down", replica=rep.rid,
                             executor=rep.executor,
                             error=type(err).__name__)
        threading.Thread(target=self._reload, args=(rep,), daemon=True,
                         name=f"rdt-serve-reload-{rep.rid}").start()

    def _reload(self, rep: _ReplicaState) -> None:
        """Background: wait out the executor restart and reload the
        servable, then hand the replica back to the dispatcher. Routed
        through the pool's live-member view: an executor that was RETIRED
        (drained out of the session) never comes back under its old handle,
        so the replica re-binds onto a surviving member and loads there —
        probing the corpse until the grace expired was exactly the
        fixed-identity bug this replaces."""
        deadline = time.monotonic() + self._reroute_grace_s
        last: Optional[BaseException] = None
        fails = 0
        while time.monotonic() < deadline:
            if self._closed:
                return  # session gone: stop dialing a stopped runtime
            try:
                replica = rep.replica
                replica.call("serve_load", rep.rid, self.export_dir,
                             timeout=60.0)
                self._events.put(("replica_up", rep, None))
                return
            except Exception as e:  # noqa: BLE001 - keep probing the restart
                last = e
                fails += 1
                if self._maybe_rebind(rep, fails):
                    # fresh target: it earns its own probe allowance (a
                    # carried-over count would ping-pong the replica
                    # between live members on every failed probe)
                    fails = 0
                time.sleep(0.5)
        logger.error("replica %s did not come back within %.0fs: %s",
                     rep.rid, self._reroute_grace_s, last)
        self._events.put(("replica_up", rep, last))

    def _live_executors(self) -> List:
        """The owning session's current pool members (empty without one)."""
        if self._session is None:
            return []
        try:
            return [h for h in list(self._session.executors)
                    if getattr(h, "name", None)]
        except Exception:  # noqa: BLE001 - a stopping session reads as none
            return []

    def _maybe_rebind(self, rep: _ReplicaState, fails: int) -> bool:
        """Re-home a reloading replica whose executor left the pool: once
        the bound executor is no longer a live member (retired/reaped), or
        keeps refusing while live alternatives exist, bind the replica to
        the live member hosting the fewest replicas and let the reload loop
        land it there (True = the binding changed). The dispatcher reads
        ``rep.replica`` concurrently — a plain attribute swap, and either
        handle is safe to dial (a lost submit re-routes through the
        ordinary fault path)."""
        live = self._live_executors()
        if not live:
            return False
        names = {h.name for h in live}
        still_member = rep.executor in names
        # a live member may just be restarting in place: give it a few
        # probes before abandoning locality; a NON-member never returns
        if still_member and fails < 4:
            return False
        counts: Dict[str, int] = {}
        for r in self._replicas:
            counts[r.executor] = counts.get(r.executor, 0) + 1
        target = min(live, key=lambda h: (counts.get(h.name, 0)
                                          if h.name != rep.executor
                                          else len(self._replicas) + 1))
        if target.name == rep.executor:
            return False
        logger.warning("replica %s re-homing from %s executor %s to %s",
                       rep.rid, "retired" if not still_member else "dead",
                       rep.executor, target.name)
        if still_member:
            # abandoning a LIVE member (persistent refusals, e.g. a long
            # GC pause): best-effort unload there, or a merely-unreachable
            # process would keep the rid's servable weights in RAM forever
            try:
                rep.replica.call("serve_unload", rep.rid, timeout=10.0)
            except Exception:  # noqa: BLE001 - it may really be dead
                pass
        rep.replica = target
        rep.executor = target.name
        return True

    def _on_replica_up(self, rep: _ReplicaState,
                       err: Optional[BaseException]) -> None:
        rep.reloading = False
        if err is None:
            rep.ready = True
            rep.reloads += 1
            rep.inflight = 0
            metrics.record_event("replica_up", replica=rep.rid,
                                 executor=rep.executor)
            logger.info("replica %s reloaded and back in rotation", rep.rid)

    # -- hot swap (dispatcher side) -------------------------------------------
    def _on_swap(self, new_reps: List[_ReplicaState], export_dir: str,
                 version: int, tag: Optional[str], done: Future) -> None:
        """The atomic half of :meth:`hot_swap`: one dispatcher step swaps
        the routing table, so a dispatch either chose the old version or
        the new one — never a mix, never a gap."""
        old = self._replicas
        self._replicas = new_reps
        self.export_dir = export_dir
        self._version = version
        self._active_tag = tag
        self._swaps += 1
        self._retiring.append(
            (time.monotonic() + self._swap_drain_s, old, version - 1))
        metrics.inc("serve_hot_swaps_total")
        metrics.record_event("hot_swap", session=self.name, version=version,
                             export_dir=export_dir, tag=tag or "")
        logger.info("serving session %s hot-swapped to v%d (%s%s); v%d "
                    "retiring behind %d in-flight dispatch(es)", self.name,
                    version, export_dir, f", tag={tag}" if tag else "",
                    version - 1, sum(r.inflight for r in old))
        done.set_result({"version": version, "export_dir": export_dir,
                         "tag": tag,
                         "replicas": [r.rid for r in new_reps]})

    def _retire_swapped(self) -> None:
        """Unload swapped-out versions once their in-flight dispatches
        drained (or the ``RDT_SERVE_SWAP_DRAIN_S`` deadline passed — the
        straggler requests still complete; only the registry entry goes)."""
        if not self._retiring:
            return
        keep = []
        for deadline, reps, ver in self._retiring:
            if all(r.inflight <= 0 for r in reps) \
                    or time.monotonic() >= deadline:
                # the unloads are RPCs with their own timeouts: background
                # thread, never the dispatcher loop
                threading.Thread(
                    target=self._unload_replicas, args=(reps, ver),
                    daemon=True,
                    name=f"rdt-serve-retire-{self.name}-v{ver}").start()
            else:
                keep.append((deadline, reps, ver))
        self._retiring = keep

    def _unload_replicas(self, reps: List[_ReplicaState], ver: int) -> None:
        for rep in reps:
            try:
                rep.replica.call("serve_unload", rep.rid, timeout=10.0)
            except Exception:  # noqa: BLE001 - executor may be gone
                pass
        logger.info("serving session %s retired servable v%d "
                    "(%d replica(s) unloaded)", self.name, ver, len(reps))

    # -- hedging --------------------------------------------------------------
    def _hedge_deadline(self) -> Optional[float]:
        """Seconds after which an in-flight dispatch earns a hedge, or None
        while hedging is off / unwarmed / pointless (a single replica)."""
        if not self._hedge_on or len(self._replicas) < 2:
            return None
        if len(self._batch_lat) < _HEDGE_MIN_SAMPLES:
            return None
        return max(self._hedge_mult * _quantile(self._batch_lat,
                                                self._hedge_q),
                   self._hedge_min_s)

    def _maybe_hedge(self) -> None:
        if self._shedding():
            return  # hedges amplify overload; suppressed while saturated
        deadline = self._hedge_deadline()
        if deadline is None:
            return
        now = time.monotonic()
        for d in list(self._inflight.values()):
            if d.done or d.hedged or not d.attempts:
                continue
            if now - d.t_first >= deadline:
                # count (and retire) the hedge only once it is really in
                # flight: with the sibling replica reloading/at-fault the
                # dispatch stays eligible and retries on a later tick
                if self._submit(d, hedge=True):
                    d.hedged = True
                    self._stats["hedged"] += 1
                    metrics.inc("serve_hedged_total")
                    metrics.record_event("hedge", dispatch=d.id,
                                         rows=d.rows)

    # -- reporting / teardown -------------------------------------------------
    def _report(self) -> Dict[str, Any]:
        lat = sorted(self._req_lat)
        occ = self._occupancy
        out = dict(self._stats)
        with self._adm_lock:
            shed = self._shed_count
            outstanding = self._outstanding
        # a shed request IS a failed request from the caller's view, so
        # ``failed`` includes ``shed`` — a clean overload run reads
        # failed == shed (nothing failed except typed rejections)
        out["shed"] = shed
        out["failed"] = out["failed"] + shed
        out.update({
            # which model answers right now: the active servable's version,
            # bundle dir, and the tag the swapper attached (partial_fit's
            # source epoch) — what the bench/chaos legs assert on
            "servable": {"version": self._version,
                         "export_dir": self.export_dir,
                         "tag": self._active_tag},
            "hot_swaps": self._swaps,
            "retiring_replicas": sum(len(reps)
                                     for _, reps, _ in self._retiring),
            "outstanding": outstanding,
            "max_queue": self._max_queue,
            "p50_ms": round(_quantile(lat, 0.50) * 1000.0, 3),
            "p99_ms": round(_quantile(lat, 0.99) * 1000.0, 3),
            "mean_batch_occupancy": (round(sum(occ) / len(occ), 2)
                                     if occ else 0.0),
            "max_batch_occupancy": max(occ) if occ else 0,
            "queue_depth": len(self._pending) + len(self._inflight),
            "queue_depth_peak": self._queue_depth_peak,
            "replicas": [{
                "replica": r.rid,
                "executor": r.executor,
                "ready": r.ready,
                "requests": r.requests,
                "batches": r.batches,
                "rows": r.rows,
                "hedges": r.hedges,
                "inflight": r.inflight,
                "inflight_peak": r.inflight_peak,
                "reloads": r.reloads,
            } for r in self._replicas],
        })
        return out

    def _drain_stop(self) -> None:
        err = ServingError("serving session closed with requests in flight")
        for req in self._pending:
            if not req.fut.done():
                req.fut.set_exception(err)
            req.finish(failed=True)
        self._pending = []
        for d in list(self._inflight.values()) + self._parked:
            if not d.done:
                for req, _ in d.parts:
                    if not req.fut.done():
                        req.fut.set_exception(err)
                    req.finish(failed=True)
        self._inflight.clear()
        self._parked = []
        # requests enqueued behind the stop event would otherwise hold
        # futures nobody ever completes
        while True:
            try:
                ev = self._events.get_nowait()
            except queue.Empty:
                break
            if ev[0] == "req":
                if not ev[1].fut.done():
                    ev[1].fut.set_exception(err)
                ev[1].finish(failed=True)
            elif ev[0] == "swap_prep":
                if not ev[1].done():
                    ev[1].set_exception(
                        ServingError("serving session closed mid-swap"))
            elif ev[0] == "swap":
                # the new version DID load on the replicas: unload it (in
                # the background — these are RPCs) instead of leaving its
                # weights pinned in executor RAM forever
                threading.Thread(
                    target=self._unload_replicas, args=(ev[1], ev[3]),
                    daemon=True,
                    name=f"rdt-serve-drainswap-{self.name}").start()
                if not ev[5].done():
                    ev[5].set_exception(
                        ServingError("serving session closed mid-swap"))
            elif ev[0] == "report":
                ev[1].set_result(self._report())
