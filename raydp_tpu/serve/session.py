"""Driver-side serving sessions: micro-batched, hedged inference over the
executor pool.

:class:`ServingSession` loads an exported servable
(``estimator.export_serving(dir)``) onto N executor-resident replicas and
exposes a thread-safe ``predict(batch)`` / ``predict_async(rows)`` API over
the existing actor RPC plane. Mechanisms, each reusing an ETL-plane design:

- **dynamic micro-batching** — concurrent requests coalesce into one device
  dispatch up to ``RDT_SERVE_MAX_BATCH`` rows or an
  ``RDT_SERVE_BATCH_TIMEOUT_MS`` latency budget; the batched output demuxes
  back per request. The replica side stages decode/H2D for the next batch
  on a ``DevicePrefetcher`` thread while the jitted apply runs (PR 1).
- **multi-version weighted routing** — the session keeps N live *version
  groups* (servable version, its replicas, a routing weight) and assigns
  each dispatch a version by smooth weighted round-robin BEFORE choosing a
  replica; a request is answered by exactly one version, re-routes and
  hedges stay inside that version's replica set, and a canary at weight
  0.1 therefore answers ~10% of dispatches and 0% of the baseline's
  (doc/serving.md "Guarded rollouts").
- **replica routing + hedged requests** — dispatches land on the
  least-busy replica of their version (per-replica in-flight counters,
  ties rotating — the PR 5 scheduler's shape); a dispatch older than
  ``max(RDT_SERVE_HEDGE_MULTIPLIER × latency-quantile,
  RDT_SERVE_HEDGE_MIN_MS)`` is hedged onto a second replica of the SAME
  version, first responder wins, the loser's result is discarded and
  counted (PR 5's speculation, re-aimed at tail latency).
- **fault path** — a replica that dies mid-request (connection lost, or a
  restarted executor answering ``ReplicaNotLoaded``) re-routes the dispatch
  through the same hedge machinery instead of surfacing an error; the
  replica reloads in the background (its OWN version's bundle) and rejoins
  the rotation. Requests fail only when every replica of their version has
  refused within the re-route grace.
- **observability** — per-replica request/batch/row counters, per-VERSION
  request/error counters and latency windows (the rollout judgment base),
  batch occupancy and queue-depth gauges, and request p50/p99 in
  :meth:`serving_report` (the ``shuffle_stage_report`` twin), plus
  ``serve:batch`` / ``serve:hedge`` trace spans.

All routing/hedging/demux state is owned by ONE dispatcher thread fed by an
event queue — RPC completion callbacks (which run on client read-loop
threads) only enqueue, so no lock ordering exists to get wrong and the
read loops never block.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from raydp_tpu import knobs, metrics, profiler
from raydp_tpu.log import get_logger
from raydp_tpu.runtime.rpc import ConnectionLost, RemoteError

logger = get_logger("serve.session")

#: completed-batch latencies required before the hedge deadline is trusted
#: (below this the quantile is noise and hedging would fire on warmup jitter)
_HEDGE_MIN_SAMPLES = 8
#: bounded latency reservoirs (batch + request + per-version) for the
#: quantile/report
_LAT_WINDOW = 2048


class ServingError(RuntimeError):
    """A request failed on every live replica within the re-route grace."""


class ServingOverloaded(ServingError):
    """A request was shed at admission: the session's outstanding queue
    (accepted, unfinished requests) is at ``RDT_SERVE_MAX_QUEUE``. Typed
    and RETRIABLE by contract — unlike :class:`ServingError` this is not a
    verdict on the request, only on the moment: the queue drains as
    batches complete, so back off and retry (or route elsewhere)."""


#: ``RemoteError.exc_type`` values that mark a replica/infrastructure
#: failure worth re-routing: a restarted executor's empty registry, and the
#: chaos plane's transient ``raise`` (doc/serving.md failure table). Any
#: other remote exception is a deterministic application error — replaying
#: it on another replica replays the error, so it fails fast instead.
_REROUTE_EXC_TYPES = ("ReplicaNotLoaded", "InjectedFault")


def _reroutable(err: BaseException) -> bool:
    if isinstance(err, (ConnectionLost, OSError)):
        return True
    return isinstance(err, RemoteError) \
        and err.exc_type in _REROUTE_EXC_TYPES


def _as_table(data) -> pa.Table:
    if isinstance(data, pa.Table):
        return data
    if isinstance(data, dict):
        return pa.table({k: np.asarray(v) for k, v in data.items()})
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:  # pragma: no cover - pandas is a hard dep elsewhere
        pass
    raise TypeError(f"cannot serve rows of type {type(data)}; pass a "
                    "pyarrow Table, pandas DataFrame, or dict of arrays")


def _encode(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def _quantile(sample: Sequence[float], q: float) -> float:
    s = sorted(sample)
    if not s:
        return 0.0
    return s[min(len(s) - 1, int(q * len(s)))]


class _Request:
    __slots__ = ("table", "fut", "t_enq", "rows", "span")

    def __init__(self, table: pa.Table, fut: Future):
        self.table = table
        self.fut = fut
        self.t_enq = time.monotonic()
        self.rows = table.num_rows
        # the request's serve:predict span opens on the caller's thread
        # (joining the caller's trace, or minting one) and closes when the
        # demuxed result lands; its context is what the dispatcher
        # activates around the batch submit, so serve:batch / serve:hedge /
        # replica serve:apply all parent here
        self.span = profiler.open_span("serve:predict", "serve",
                                       rows=self.rows)

    @property
    def ctx(self):
        return profiler.span_context(self.span)

    def finish(self, **args) -> None:
        profiler.close_span(self.span, **args)


class _Attempt:
    __slots__ = ("replica", "t0", "hedge")

    def __init__(self, replica: "_ReplicaState", t0: float, hedge: bool):
        self.replica = replica
        self.t0 = t0
        self.hedge = hedge


class _Dispatch:
    """One coalesced batch in flight (possibly on two replicas at once).
    ``version`` pins it to ONE version group: every attempt — first route,
    re-route, hedge — draws from that group's replicas, so a response is
    always the output of exactly one servable version."""

    __slots__ = ("id", "payload", "rows", "parts", "attempts", "tried",
                 "hedged", "done", "t_first", "last_error", "version")

    def __init__(self, did: int, payload: bytes, rows: int, parts,
                 version: int):
        self.id = did
        self.payload = payload
        self.rows = rows
        self.parts = parts            # [(request, row offset)]
        self.attempts: Dict[int, _Attempt] = {}
        self.tried: set = set()       # replica ids an attempt ran on
        self.hedged = False
        self.done = False
        self.t_first = time.monotonic()
        self.last_error: Optional[BaseException] = None
        self.version = version


class _ReplicaState:
    """Driver-side view of one replica: its actor handle, its in-flight
    count, and its readiness (False while the executor restarts/reloads).
    ``export_dir`` is the bundle THIS replica serves — the background
    reload must restore a canary replica's canary bundle, not whatever the
    session's primary happens to be."""

    def __init__(self, rid: str, replica, executor_name: str,
                 export_dir: str):
        self.rid = rid
        #: the ActorHandle — named `replica` so rdtlint's rpc-surface rule
        #: resolves `replica.submit("serve_predict", ...)` call sites against
        #: the actor surface (tools/rdtlint/config.py RPC_RECEIVER_SURFACES)
        self.replica = replica
        self.executor = executor_name
        self.export_dir = export_dir
        self.inflight = 0
        self.inflight_peak = 0
        self.ready = True
        self.reloading = False
        # counters for serving_report()
        self.requests = 0
        self.batches = 0
        self.rows = 0
        self.hedges = 0
        self.reloads = 0


class _VersionGroup:
    """One live servable version: its replicas, its routing weight, and the
    per-version health windows the rollout judgment reads. All fields are
    dispatcher-owned after registration."""

    def __init__(self, version: int, export_dir: str, tag: Optional[str],
                 replicas: List[_ReplicaState], weight: float = 1.0):
        self.version = version
        self.export_dir = export_dir
        self.tag = tag
        self.weight = float(weight)
        self.replicas = replicas
        #: smooth-WRR credit: deterministic proportional interleave, so a
        #: weight-0.25 canary answers exactly one dispatch in four (no RNG
        #: — tests and the judgment windows see the configured split)
        self.wrr = 0.0
        #: next scale-up replica index (initial replicas claimed 0..n-1)
        self.rid_seq = len(replicas)
        # per-version health counters/windows (the judgment base: a global
        # latency window would let a healthy baseline mask a regressing
        # canary)
        self.requests = 0
        self.failed = 0
        self.req_lat: List[float] = []


class ServingSession:
    """See module docstring. Construct with a live ETL session (or an
    explicit executor-handle list) and a servable ``export_dir``:

        est.fit_on_frame(train_df)
        est.export_serving("/shared/model-v1")
        srv = ServingSession("/shared/model-v1", session=session)
        preds = srv.predict(rows)          # or predict_async(rows) -> Future
        srv.rollout("/shared/model-v2")    # guarded canary → promote/rollback
        srv.serving_report(); srv.close()

    Knobs (all re-read at construction; doc/serving.md): batching
    ``RDT_SERVE_MAX_BATCH`` / ``RDT_SERVE_BATCH_TIMEOUT_MS``, routing
    ``RDT_SERVE_MAX_INFLIGHT``, hedging ``RDT_SERVE_HEDGE`` /
    ``RDT_SERVE_HEDGE_QUANTILE`` / ``RDT_SERVE_HEDGE_MULTIPLIER`` /
    ``RDT_SERVE_HEDGE_MIN_MS``, fault path ``RDT_SERVE_REROUTE_GRACE_S``,
    overload shedding ``RDT_SERVE_MAX_QUEUE``, replica staging
    ``RDT_SERVE_PREFETCH``; the rollout/autoscale knobs are read by
    :class:`~raydp_tpu.serve.rollout.RolloutController` /
    :class:`~raydp_tpu.serve.autoscale.ServingAutoscaler`."""

    def __init__(self, export_dir: str, session=None,
                 executors: Optional[List] = None,
                 num_replicas: Optional[int] = None,
                 name: str = "serving"):
        if executors is None:
            if session is None:
                from raydp_tpu.context import active_session
                session = active_session()
            if session is None:
                raise ValueError("pass session= or executors= (no active "
                                 "raydp_tpu session to serve from)")
            executors = list(session.executors)
        if not executors:
            raise ValueError("serving needs at least one executor")
        #: the live-member view replica reloads route through: when the
        #: executor hosting a replica is RETIRED from the pool (not merely
        #: restarting), the background reload re-binds the replica onto a
        #: surviving member instead of probing the corpse until the
        #: re-route grace expires. None with an explicit executors= list
        #: (no pool to consult — reloads then probe the fixed handle only).
        self._session = session
        if num_replicas is not None:
            if num_replicas < 1:
                raise ValueError("num_replicas must be >= 1")
            executors = [executors[i % len(executors)]
                         for i in range(num_replicas)]
        self.export_dir = export_dir
        self.name = name
        self._max_batch = max(1, int(knobs.get("RDT_SERVE_MAX_BATCH")))
        self._timeout_s = max(
            0.0, float(knobs.get("RDT_SERVE_BATCH_TIMEOUT_MS")) / 1000.0)
        self._max_inflight = max(1, int(knobs.get("RDT_SERVE_MAX_INFLIGHT")))
        self._hedge_on = bool(knobs.get("RDT_SERVE_HEDGE"))
        self._hedge_q = float(knobs.get("RDT_SERVE_HEDGE_QUANTILE"))
        self._hedge_mult = float(knobs.get("RDT_SERVE_HEDGE_MULTIPLIER"))
        self._hedge_min_s = max(
            0.0, float(knobs.get("RDT_SERVE_HEDGE_MIN_MS")) / 1000.0)
        self._reroute_grace_s = float(knobs.get("RDT_SERVE_REROUTE_GRACE_S"))
        self._max_queue = max(0, int(knobs.get("RDT_SERVE_MAX_QUEUE")))
        # overload shedding state — touched from REQUEST threads (admission
        # in predict_async, decrements from future callbacks), never by the
        # dispatcher alone, so unlike the dispatcher-owned state below it
        # needs its own lock
        self._adm_lock = threading.Lock()
        self._outstanding = 0  # guarded-by: _adm_lock
        self._shed_count = 0   # guarded-by: _adm_lock
        #: serializes hot_swap()/load_version()/scale_replicas() callers —
        #: the structural changes themselves apply on the dispatcher
        #: thread; this only orders concurrent load/version allocations
        self._swap_lock = threading.Lock()
        self._next_version = 2  # guarded-by: _swap_lock
        self._swap_drain_s = max(
            0.0, float(knobs.get("RDT_SERVE_SWAP_DRAIN_S")))

        reps: List[_ReplicaState] = []
        loads = []
        for i, h in enumerate(executors):
            rid = f"{name}-r{i}"
            rep = _ReplicaState(rid, h, getattr(h, "name", None) or f"ex{i}",
                                export_dir)
            # parallel load: each replica pays its jax import + jit once,
            # concurrently, instead of serializing session bring-up
            replica = rep.replica
            loads.append(replica.submit("serve_load", rid, export_dir))
            reps.append(rep)
        for f in loads:
            f.result(timeout=180.0)

        # dispatcher-owned state (no locks: one thread mutates it)
        self._events: "queue.Queue" = queue.Queue()
        self._pending: List[_Request] = []     # awaiting coalescing
        self._pending_rows = 0
        self._inflight: Dict[int, _Dispatch] = {}
        self._parked: List[_Dispatch] = []     # waiting for a replica
        self._rr = itertools.count()
        self._did = itertools.count()
        # version-group state (dispatcher-owned after construction): the
        # PRIMARY group is the baseline every new session starts with;
        # canaries register beside it via load_version()
        self._primary = _VersionGroup(1, export_dir, None, reps, weight=1.0)
        self._groups: List[_VersionGroup] = [self._primary]
        self._swaps = 0
        #: (drain deadline, replicas, version) of swapped-out servables
        self._retiring: List = []
        self._closed = False
        self._batch_lat: List[float] = []      # bounded; hedge quantile base
        self._req_lat: List[float] = []        # bounded; report p50/p99
        self._occupancy: List[int] = []        # rows per dispatched batch
        self._queue_depth_peak = 0
        self._stats = {"requests": 0, "batches": 0, "rows": 0,
                       "hedged": 0, "hedge_won": 0, "hedge_lost": 0,
                       "rerouted": 0, "failed": 0}
        self._dispatcher = threading.Thread(
            target=self._run, daemon=True, name=f"rdt-serve-dispatch-{name}")
        self._dispatcher.start()

    # ---- public API ---------------------------------------------------------
    def predict_async(self, rows) -> Future:
        """Enqueue rows (Table / DataFrame / dict of arrays); the Future
        resolves to a float32 prediction array, one entry per input row.
        Thread-safe; callable from any number of request threads.

        Overload shedding: past ``RDT_SERVE_MAX_QUEUE`` outstanding
        (accepted, unfinished) requests this fails fast with the typed
        retriable :class:`ServingOverloaded` instead of growing the
        dispatcher queue without bound — a burst degrades to rejections,
        never to a collapsing dispatcher (doc/serving.md "Overload")."""
        table = _as_table(rows)
        fut: Future = Future()
        if table.num_rows == 0:
            fut.set_result(np.empty((0,), np.float32))
            return fut
        if self._closed:
            raise ServingError("serving session is closed")
        with self._adm_lock:
            if self._max_queue > 0 and self._outstanding >= self._max_queue:
                self._shed_count += 1
                outstanding = self._outstanding
                shed = True
            else:
                self._outstanding += 1
                shed = False
        if shed:
            metrics.inc("serve_shed_total")
            metrics.record_event("overload_shed", session=self.name,
                                 outstanding=outstanding,
                                 max_queue=self._max_queue)
            raise ServingOverloaded(
                f"serving session {self.name!r} is saturated "
                f"({outstanding} outstanding requests >= "
                f"RDT_SERVE_MAX_QUEUE={self._max_queue}); retry with "
                "backoff")
        # whichever way the request ends (demuxed result, re-route
        # exhaustion, close) the admission slot releases with its future
        fut.add_done_callback(self._release_admission)
        self._events.put(("req", _Request(table, fut)))
        if self._closed and not fut.done():
            # close() raced the enqueue: the request may sit behind the
            # stop event on a queue nobody drains anymore — fail it here
            # rather than leave a Future that never resolves (the winner
            # path guards set_result with done(), so the benign double
            # race resolves to whichever side got there first)
            try:
                fut.set_exception(ServingError("serving session is closed"))
            except Exception:  # noqa: BLE001 - lost the race: it completed
                pass
        return fut

    def predict(self, rows, timeout: Optional[float] = None) -> np.ndarray:
        """Synchronous :meth:`predict_async`."""
        return self.predict_async(rows).result(timeout=timeout)

    def _release_admission(self, _fut) -> None:
        with self._adm_lock:
            self._outstanding = max(0, self._outstanding - 1)

    def _shedding(self) -> bool:
        """Saturated right now? While True the dispatcher suppresses
        hedging — a hedge is a duplicate dispatch, and duplicating work
        while shedding new requests amplifies exactly the overload the
        shed exists to absorb. The rollout judgment reads the same gate
        (via ``serving_report``): saturation inflates BOTH versions'
        windows, so a health verdict taken now would roll back a healthy
        canary for the pool's overload."""
        with self._adm_lock:
            return self._max_queue > 0 \
                and self._outstanding >= self._max_queue

    def hot_swap(self, export_dir: str, tag: Optional[str] = None,
                 timeout: float = 180.0) -> Dict[str, Any]:
        """Atomically roll the session onto a new servable under live
        traffic: load the bundle at ``export_dir`` BESIDE the active one on
        every primary replica's executor (distinct replica ids — the
        registry holds both), shift all new primary dispatches to it in one
        dispatcher step, and retire the old version in the background once
        its in-flight work drains (bounded by ``RDT_SERVE_SWAP_DRAIN_S``;
        stragglers still complete, the registry entry just goes away). No
        request is dropped: every response comes from exactly one version —
        the one its dispatch was routed to. ``tag`` annotates the version
        in :meth:`serving_report` (``partial_fit`` passes the source
        epoch). Thread-safe; concurrent swaps serialize in call order.

        This is the UNGUARDED cut-over (100% of primary traffic the moment
        the load lands); :meth:`rollout` is the guarded ramp on top."""
        if self._closed:
            raise ServingError("serving session is closed")
        with self._swap_lock:
            v = self._next_version
            self._next_version += 1
            new_reps = self._load_beside_primary(export_dir, timeout, v)
            done: Future = Future()
            self._events.put(("swap", new_reps, export_dir, v, tag, done))
            return done.result(timeout=30.0)

    def _load_beside_primary(self, export_dir: str, timeout: float,
                             v: int) -> List["_ReplicaState"]:
        """Load one replica of ``export_dir`` beside each primary replica
        (caller thread — these are blocking RPCs) under the
        caller-allocated version number ``v``. Returns the loaded
        ``_ReplicaState`` list; a partial load is rolled back before the
        error surfaces. Callers hold ``_swap_lock`` (the version
        allocation and replica-id namespace)."""
        # replica handles/executors are dispatcher-owned state (reloads
        # re-bind them): snapshot them ON the dispatcher thread instead
        # of racing _maybe_rebind from here
        snap: Future = Future()
        self._events.put(("swap_prep", snap))
        members = snap.result(timeout=30.0)
        new_reps: List[_ReplicaState] = []
        loads = []
        for i, (handle, executor) in enumerate(members):
            rid = f"{self.name}-v{v}-r{i}"
            rep = _ReplicaState(rid, handle, executor, export_dir)
            # parallel load beside the active servable — the old rid
            # keeps serving while the new one pays its jit
            replica = rep.replica
            loads.append(replica.submit("serve_load", rid, export_dir))
            new_reps.append(rep)
        errors = []
        for f in loads:
            try:
                f.result(timeout=timeout)
            except Exception as e:  # noqa: BLE001 - collected below
                errors.append(e)
        if errors:
            # never leave a half-loaded version pinning executor RAM:
            # unload whatever DID land, then surface the failure
            threading.Thread(
                target=self._unload_replicas, args=(new_reps, v),
                daemon=True,
                name=f"rdt-serve-loadfail-{self.name}-v{v}").start()
            raise ServingError(
                f"loading {export_dir!r} failed on "
                f"{len(errors)}/{len(loads)} replica(s); the partial "
                f"load was rolled back") from errors[0]
        return new_reps

    # ---- guarded rollout / weighted versions (doc/serving.md) ---------------
    def load_version(self, export_dir: str, weight: float,
                     tag: Optional[str] = None,
                     timeout: float = 180.0) -> Dict[str, Any]:
        """Load ``export_dir`` as a NEW live version group beside the
        primary (one replica per primary replica, same executors) and start
        routing ``weight`` of dispatch traffic to it. The building block
        under :meth:`rollout`; pair with :meth:`set_weight` /
        :meth:`promote_version` / :meth:`drop_version`."""
        if self._closed:
            raise ServingError("serving session is closed")
        if weight < 0:
            raise ValueError("weight must be >= 0")
        with self._swap_lock:
            v = self._next_version
            self._next_version += 1
            new_reps = self._load_beside_primary(export_dir, timeout, v)
            group = _VersionGroup(v, export_dir, tag, new_reps,
                                  weight=weight)
            done: Future = Future()
            self._events.put(("add_group", group, done))
            return done.result(timeout=30.0)

    def set_weight(self, version: int, weight: float) -> Dict[str, Any]:
        """Re-weight a live version group (effective on the next dispatch,
        in one dispatcher step). Weight 0 parks a version out of NEW
        traffic without unloading it — its in-flight work still completes."""
        if self._closed:
            raise ServingError("serving session is closed")
        if weight < 0:
            raise ValueError("weight must be >= 0")
        done: Future = Future()
        self._events.put(("set_weight", int(version), float(weight), done))
        return done.result(timeout=30.0)

    def promote_version(self, version: int) -> Dict[str, Any]:
        """Make a live canary group THE primary (weight 1.0) and retire the
        old primary through the ordinary swap/retire machinery (drain, then
        unload, bounded by ``RDT_SERVE_SWAP_DRAIN_S``). One dispatcher
        step: a dispatch routed before it answers from the version it
        chose; after it the canary is the baseline."""
        if self._closed:
            raise ServingError("serving session is closed")
        done: Future = Future()
        self._events.put(("promote", int(version), done))
        return done.result(timeout=30.0)

    def drop_version(self, version: int) -> Dict[str, Any]:
        """Take a canary group OUT: weight to 0, replicas retired (in-flight
        dispatches complete, then unload — the rollback half of a guarded
        rollout). Parked dispatches that chose this version re-home to the
        primary (they were never answered, so no response mixes versions).
        The primary cannot be dropped."""
        if self._closed:
            raise ServingError("serving session is closed")
        done: Future = Future()
        self._events.put(("drop_group", int(version), done))
        return done.result(timeout=30.0)

    def rollout(self, export_dir: str, tag: Optional[str] = None,
                timeout: Optional[float] = None,
                **opts) -> Dict[str, Any]:
        """Guarded deployment of ``export_dir``: load it as a canary at
        ``RDT_SERVE_CANARY_WEIGHT``, ramp its traffic share on the
        ``RDT_SERVE_ROLLOUT_RAMP`` schedule judging per-version error-rate
        and p99 at every step, then auto-promote — or auto-roll-back on the
        first unhealthy verdict (weight→0, unload, ``rollout_rollback``
        event + blackbox bundle). Blocking; returns the outcome record.
        See :class:`~raydp_tpu.serve.rollout.RolloutController`."""
        from raydp_tpu.serve.rollout import RolloutController

        return RolloutController(self, export_dir, tag=tag,
                                 timeout=timeout, **opts).run()

    def autoscale(self, min_replicas: Optional[int] = None,
                  max_replicas: Optional[int] = None):
        """Start a :class:`~raydp_tpu.serve.autoscale.ServingAutoscaler`
        driving this session's per-version replica counts from queue
        depth. Returns the started controller (caller stops it)."""
        from raydp_tpu.serve.autoscale import ServingAutoscaler

        return ServingAutoscaler(self, min_replicas=min_replicas,
                                 max_replicas=max_replicas).start()

    def scale_replicas(self, count: int,
                       timeout: float = 180.0) -> Dict[str, Any]:
        """Set EVERY live version group to ``count`` replicas (the
        autoscaler's actuator). Growth loads new replicas onto the
        least-loaded live executors (blocking RPCs on the caller thread);
        shrink drains the least-busy replicas through the retire path —
        their in-flight dispatches complete before the unload. Every group
        gets the same count so a low-weight canary is never capacity-bound:
        queueing inside the canary would inflate exactly the p99 window
        the rollout judgment reads."""
        if count < 1:
            raise ValueError("count must be >= 1")
        if self._closed:
            raise ServingError("serving session is closed")
        with self._swap_lock:
            snap: Future = Future()
            self._events.put(("scale_prep", snap))
            groups = snap.result(timeout=30.0)
            live = self._live_executors()
            # replica count per executor name, across every group — growth
            # packs the least-loaded member first
            counts: Dict[str, int] = {}
            handles: Dict[str, Any] = {}
            for _v, _dir, _seq, members in groups:
                for handle, executor in members:
                    counts[executor] = counts.get(executor, 0) + 1
                    handles.setdefault(executor, handle)
            for h in live:
                counts.setdefault(h.name, 0)
                handles[h.name] = h
            per_version: Dict[int, Any] = {}
            for v, export_dir, rid_seq, members in groups:
                have = len(members)
                if count > have:
                    new_reps: List[_ReplicaState] = []
                    loads = []
                    for k in range(count - have):
                        executor = min(counts, key=counts.get)
                        counts[executor] += 1
                        rid = f"{self.name}-v{v}-r{rid_seq + k}"
                        rep = _ReplicaState(rid, handles[executor],
                                            executor, export_dir)
                        replica = rep.replica
                        loads.append(
                            replica.submit("serve_load", rid, export_dir))
                        new_reps.append(rep)
                    errors = []
                    for f in loads:
                        try:
                            f.result(timeout=timeout)
                        except Exception as e:  # noqa: BLE001 - below
                            errors.append(e)
                    if errors:
                        threading.Thread(
                            target=self._unload_replicas,
                            args=(new_reps, v), daemon=True,
                            name=f"rdt-serve-scalefail-{self.name}").start()
                        raise ServingError(
                            f"scale-up of v{v} failed loading "
                            f"{len(errors)}/{len(loads)} replica(s)"
                        ) from errors[0]
                    done: Future = Future()
                    self._events.put(
                        ("add_replicas", v, new_reps, rid_seq + count - have,
                         done))
                    per_version[v] = done.result(timeout=30.0)
                elif count < have:
                    done = Future()
                    self._events.put(("shrink_group", v, have - count, done))
                    per_version[v] = done.result(timeout=30.0)
                else:
                    per_version[v] = {"replicas": have, "unchanged": True}
            return {"replicas": count, "versions": per_version}

    def serving_report(self) -> Dict[str, Any]:
        """Counters + latency snapshot (the ``shuffle_stage_report`` twin
        for the serving plane; columns documented in doc/serving.md),
        including one row per live VERSION group — requests, failures,
        p50/p99 over its own window, weight, replica counts — the rollout
        judgment's input."""
        if self._closed and not self._dispatcher.is_alive():
            return self._report()  # post-close snapshot: nothing mutates
        done: Future = Future()
        self._events.put(("report", done))
        return done.result(timeout=30.0)

    def close(self, unload: bool = True) -> None:
        """Stop the dispatcher; in-flight work is failed, replicas unloaded
        (``unload=False`` keeps them for a successor session)."""
        if self._closed:
            return
        self._closed = True
        self._events.put(("stop",))
        self._dispatcher.join(timeout=30.0)
        if unload:
            # every live group's replicas plus any swapped-out version
            # still draining (the dispatcher is down: nothing retires them
            # now); single attempt each — the runtime is going away, so
            # the retry-probe path would just dial a stopping pool
            doomed = [r for g in self._groups for r in g.replicas]
            for _, reps, _ in self._retiring:
                doomed.extend(reps)
            self._retiring = []
            for rep in doomed:
                try:
                    rep.replica.call("serve_unload", rep.rid, timeout=10.0)
                except Exception:  # noqa: BLE001 - executor may be gone
                    pass

    def __enter__(self) -> "ServingSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- dispatcher internals (single thread) -------------------------------
    def _run(self) -> None:
        while True:
            timeout = self._next_wakeup()
            try:
                ev = self._events.get(timeout=timeout)
            except queue.Empty:
                ev = None
            try:
                if ev is not None:
                    kind = ev[0]
                    if kind == "stop":
                        self._drain_stop()
                        return
                    if kind == "req":
                        self._on_request(ev[1])
                    elif kind == "done":
                        self._on_done(ev[1], ev[2], ev[3], ev[4])
                    elif kind == "replica_up":
                        self._on_replica_up(ev[1], ev[2])
                    elif kind in ("swap_prep", "scale_prep"):
                        # a torn mid-rebind (handle, name) pair is what the
                        # dispatcher-thread copy exists to prevent
                        if kind == "swap_prep":
                            ev[1].set_result(
                                [(r.replica, r.executor)
                                 for r in self._primary.replicas])
                        else:
                            ev[1].set_result(
                                [(g.version, g.export_dir, g.rid_seq,
                                  [(r.replica, r.executor)
                                   for r in g.replicas])
                                 for g in self._groups])
                    elif kind == "swap":
                        self._on_swap(ev[1], ev[2], ev[3], ev[4], ev[5])
                    elif kind == "add_group":
                        self._on_add_group(ev[1], ev[2])
                    elif kind == "set_weight":
                        self._on_set_weight(ev[1], ev[2], ev[3])
                    elif kind == "promote":
                        self._on_promote(ev[1], ev[2])
                    elif kind == "drop_group":
                        self._on_drop_group(ev[1], ev[2])
                    elif kind == "add_replicas":
                        self._on_add_replicas(ev[1], ev[2], ev[3], ev[4])
                    elif kind == "shrink_group":
                        self._on_shrink_group(ev[1], ev[2], ev[3])
                    elif kind == "report":
                        ev[1].set_result(self._report())
                self._flush_batches()
                self._maybe_hedge()
                self._retry_parked()
                self._retire_swapped()
                # refresh on every loop pass (arrivals, flushes, drains
                # alike) so an idle session reads 0, not the last
                # pre-dispatch depth; labeled per session so two sessions
                # in one driver never overwrite each other's slot
                metrics.set_gauge("serve_queue_depth",
                                  len(self._pending) + len(self._inflight),
                                  label=self.name)
            except Exception:  # noqa: BLE001 - the loop must survive anything
                # a dead dispatcher bricks every current and future request;
                # per-batch/per-dispatch errors are already routed to their
                # own futures, so whatever reaches here is a bug to log,
                # never a reason to stop serving
                logger.exception("serving dispatcher error (loop continues)")

    def _next_wakeup(self) -> Optional[float]:
        """Sleep until the earliest deadline the loop owns: the oldest
        pending batch's flush, or the next hedge-eligibility instant."""
        deadlines = []
        if self._pending:
            deadlines.append(self._pending[0].t_enq + self._timeout_s)
        hedge_after = self._hedge_deadline()
        if hedge_after is not None:
            for d in self._inflight.values():
                if not d.hedged and not d.done:
                    deadlines.append(d.t_first + hedge_after)
        if self._parked:
            deadlines.append(time.monotonic() + 0.05)
        if self._retiring:
            deadlines.append(time.monotonic() + 0.05)
        if not deadlines:
            return None
        return max(0.0, min(deadlines) - time.monotonic()) or 0.001

    # -- batching -------------------------------------------------------------
    def _on_request(self, req: _Request) -> None:
        self._stats["requests"] += 1
        metrics.inc("serve_requests_total")
        self._pending.append(req)
        self._pending_rows += req.rows
        self._queue_depth_peak = max(
            self._queue_depth_peak, len(self._pending) + len(self._inflight))

    def _flush_batches(self) -> None:
        while self._pending:
            full = self._pending_rows >= self._max_batch
            aged = (time.monotonic() - self._pending[0].t_enq
                    >= self._timeout_s)
            if not (full or aged):
                return
            # coalesce only schema-equal requests: a mixed batch would fail
            # pa.concat_tables and punish the well-formed requests packed
            # with it; the other-schema requests stay pending and form
            # their own batch on a later pass of this loop
            schema = self._pending[0].table.schema
            batch: List[_Request] = []
            rows = 0
            rest: List[_Request] = []
            for r in self._pending:
                if (batch and rows + r.rows > self._max_batch) \
                        or not r.table.schema.equals(schema):
                    rest.append(r)
                    continue
                batch.append(r)
                rows += r.rows
            self._pending = rest
            self._pending_rows -= rows
            self._dispatch_new(batch, rows)

    def _dispatch_new(self, batch: List[_Request], rows: int) -> None:
        parts, off = [], 0
        for r in batch:
            parts.append((r, off))
            off += r.rows
        try:
            table = (batch[0].table if len(batch) == 1
                     else pa.concat_tables([r.table for r in batch]))
            payload = _encode(table)
        except Exception as e:  # noqa: BLE001 - a bad request fails fast
            self._stats["failed"] += len(batch)
            for r in batch:
                if not r.fut.done():
                    r.fut.set_exception(e)
            return
        # the version is chosen ONCE, at dispatch birth: whatever happens
        # to this batch later (re-route, hedge, park) stays inside the
        # chosen version's replica set
        group = self._choose_version()
        d = _Dispatch(next(self._did), payload, rows, parts, group.version)
        self._stats["batches"] += 1
        self._stats["rows"] += rows
        metrics.inc("serve_batches_total")
        metrics.inc("serve_rows_total", rows)
        metrics.observe("serve_batch_occupancy_rows", rows)
        self._occupancy.append(rows)
        if len(self._occupancy) > _LAT_WINDOW:
            del self._occupancy[:-_LAT_WINDOW]
        self._submit(d, hedge=False)

    # -- routing --------------------------------------------------------------
    def _group(self, version: int) -> Optional[_VersionGroup]:
        for g in self._groups:
            if g.version == version:
                return g
        return None

    def _vlabel(self, g: _VersionGroup) -> str:
        return f"{self.name}:v{g.version}"

    def _choose_version(self) -> _VersionGroup:
        """Smooth weighted round-robin over the live version groups: each
        candidate accrues its weight in credit, the highest credit wins and
        pays back the total — a deterministic interleave whose long- AND
        short-run split matches the weight table (nginx's algorithm). A
        weight-0 group gets nothing; with every weight 0 (transient
        rollback states) the primary serves."""
        live = [g for g in self._groups if g.weight > 0 and g.replicas]
        if not live:
            return self._primary
        if len(live) == 1:
            return live[0]
        total = 0.0
        best = None
        for g in live:
            g.wrr += g.weight
            total += g.weight
            if best is None or g.wrr > best.wrr:
                best = g
        best.wrr -= total
        return best

    def _choose(self, d: _Dispatch) -> Optional[_ReplicaState]:
        """Least-busy ready replica OF THIS DISPATCH'S VERSION not already
        carrying it, round-robin on ties, respecting the per-replica
        in-flight cap — except when EVERY ready replica is at cap, where
        the least-busy one is taken anyway (a serving request must queue,
        not park forever). A dispatch whose version group was dropped
        (rolled back) before any replica answered re-homes to the primary —
        it was never answered, so no response mixes versions."""
        g = self._group(d.version)
        if g is None or not g.replicas:
            g = self._primary
            if d.version != g.version:
                d.version = g.version
                d.tried.clear()
        reps = g.replicas
        start = next(self._rr)
        k = len(reps)
        best = None
        for allow_full in (False, True):
            for i in range(k):
                rep = reps[(start + i) % k]
                if not rep.ready or rep.rid in d.tried:
                    continue
                if not allow_full and rep.inflight >= self._max_inflight:
                    continue
                if best is None or rep.inflight < best.inflight:
                    best = rep
            if best is not None:
                return best
        return None

    def _submit(self, d: _Dispatch, hedge: bool) -> bool:
        """Route and send one attempt; True only when an attempt is
        actually in flight (the hedge accounting keys on it)."""
        rep = self._choose(d)
        if rep is None:
            if hedge:
                return False  # no second replica free: simply do not hedge
            self._park(d)
            return False
        d.tried.add(rep.rid)
        t0 = time.monotonic()
        span = "serve:hedge" if hedge else "serve:batch"
        try:
            # the span covers the driver-side submit (encode happened at
            # coalesce time); the replica-side serve:apply span carries the
            # device half of the timeline. The batch joins the FIRST
            # coalesced request's trace (a batch has one parent lane; the
            # sibling requests' spans still record their own latency), so
            # the RPC layer ships serve:batch as the remote apply's parent
            with profiler.activate(d.parts[0][0].ctx if d.parts else None):
                with profiler.trace(span, "serve", replica=rep.rid,
                                    rows=d.rows, requests=len(d.parts)):
                    replica = rep.replica
                    fut = replica.submit("serve_predict", rep.rid, d.payload)
        except (ConnectionLost, OSError) as e:
            # the executor is unreachable (restarting): take the replica out
            # of rotation, start its background reload, and re-route
            self._note_replica_error(_Attempt(rep, t0, hedge), e)
            self._attempt_failed(d, rep, e)
            return False
        rep.inflight += 1
        rep.inflight_peak = max(rep.inflight_peak, rep.inflight)
        rep.batches += 1
        rep.requests += len(d.parts)
        rep.rows += d.rows
        if hedge:
            rep.hedges += 1
        aid = id(fut)
        d.attempts[aid] = _Attempt(rep, t0, hedge)
        self._inflight[d.id] = d

        def _cb(f, did=d.id, aid=aid, rid=rep.rid):
            # client read-loop thread: enqueue only, never block
            self._events.put(("done", did, aid, rid, f))

        fut.add_done_callback(_cb)
        return True

    def _park(self, d: _Dispatch) -> None:
        """No routable replica right now (all restarting/reloading): hold
        the dispatch and retry as replicas come back, up to the grace."""
        if time.monotonic() - d.t_first > self._reroute_grace_s:
            self._fail_dispatch(d)
            return
        if d not in self._parked:
            # a parked dispatch may be re-tried on any replica again once
            # one reloads — a reloaded replica is a FRESH process
            d.tried.clear()
            self._parked.append(d)
        # parked work is the strongest signal a dead replica is still
        # needed: re-kick any reload that previously gave up, so a
        # transient full outage longer than one reload pass does not brick
        # the session for its remaining lifetime
        for g in self._groups:
            for rep in g.replicas:
                if not rep.ready and not rep.reloading:
                    rep.reloading = True
                    threading.Thread(
                        target=self._reload, args=(rep,), daemon=True,
                        name=f"rdt-serve-reload-{rep.rid}").start()

    def _retry_parked(self) -> None:
        if not self._parked:
            return
        parked, self._parked = self._parked, []
        for d in parked:
            if not d.done:
                self._submit(d, hedge=False)

    # -- completion / hedging / fault path ------------------------------------
    def _on_done(self, did: int, aid: int, rid: str, fut: Future) -> None:
        d = self._inflight.get(did)
        if d is None:
            return
        att = d.attempts.pop(aid, None)
        if att is not None:
            att.replica.inflight = max(0, att.replica.inflight - 1)
        err = fut.exception()
        if d.done:
            # the loser of a won hedge (or of a rescue): discard, count
            if err is None and att is not None:
                self._stats["hedge_lost"] += 1
                metrics.inc("serve_hedge_lost_total")
            if not d.attempts:
                self._inflight.pop(did, None)
            if err is not None:
                self._note_replica_error(att, err)
            return
        if err is None:
            d.done = True
            if att is not None and att.hedge:
                self._stats["hedge_won"] += 1
                metrics.inc("serve_hedge_won_total")
            now = time.monotonic()
            if att is not None:
                self._batch_lat.append(now - att.t0)
                if len(self._batch_lat) > _LAT_WINDOW:
                    del self._batch_lat[:-_LAT_WINDOW]
            g = self._group(d.version)
            preds = np.asarray(fut.result())
            for req, off in d.parts:
                if not req.fut.done():  # close()/race-failed futures skip
                    req.fut.set_result(preds[off:off + req.rows])
                self._req_lat.append(now - req.t_enq)
                metrics.observe("serve_request_seconds", now - req.t_enq)
                if g is not None:
                    g.req_lat.append(now - req.t_enq)
                    metrics.observe("serve_version_request_seconds",
                                    now - req.t_enq, label=self._vlabel(g))
                req.finish(replica=rid)
            if len(self._req_lat) > _LAT_WINDOW:
                del self._req_lat[:-_LAT_WINDOW]
            if g is not None:
                g.requests += len(d.parts)
                metrics.inc("serve_version_requests_total", len(d.parts),
                            label=self._vlabel(g))
                if len(g.req_lat) > _LAT_WINDOW:
                    del g.req_lat[:-_LAT_WINDOW]
            if not d.attempts:
                self._inflight.pop(did, None)
            return
        # failed attempt
        self._note_replica_error(att, err)
        self._attempt_failed(d, att.replica if att else None, err)

    def _attempt_failed(self, d: _Dispatch, rep: Optional[_ReplicaState],
                        err: BaseException) -> None:
        d.last_error = err
        if d.attempts:
            return  # a sibling copy is still racing; it may still win
        if not _reroutable(err):
            # deterministic application error (bad schema, model bug):
            # another replica would compute the same failure — fail the
            # request now instead of burning the re-route grace on it
            self._fail_dispatch(d)
            return
        if time.monotonic() - d.t_first > self._reroute_grace_s:
            self._fail_dispatch(d)
            return
        self._stats["rerouted"] += 1
        metrics.inc("serve_rerouted_total")
        logger.warning("serve dispatch %d (v%d) re-routing off %s after: %s",
                       d.id, d.version, rep.rid if rep else "?", err)
        self._submit(d, hedge=False)

    def _fail_dispatch(self, d: _Dispatch) -> None:
        d.done = True
        self._inflight.pop(d.id, None)
        self._stats["failed"] += len(d.parts)
        metrics.inc("serve_failed_total", len(d.parts))
        g = self._group(d.version)
        if g is not None:
            g.failed += len(d.parts)
            metrics.inc("serve_version_failed_total", len(d.parts),
                        label=self._vlabel(g))
        err = ServingError(
            f"request failed on every replica within "
            f"{self._reroute_grace_s:.0f}s (last error: {d.last_error})")
        err.__cause__ = d.last_error
        for req, _ in d.parts:
            if not req.fut.done():
                req.fut.set_exception(err)
            req.finish(failed=True)
        metrics.record_event("request_failed", dispatch=d.id,
                             version=d.version, requests=len(d.parts),
                             last_error=str(d.last_error)[:300])
        # the ServingError postmortem bundle (doc/observability.md) — on a
        # BACKGROUND thread: the harvest RPCs every live process with a 10s
        # timeout each, and this runs on the dispatcher event loop, which
        # must keep batching/hedging/demuxing the session's OTHER requests
        # (a hung executor is exactly the scenario that got us here).
        # Capped per label inside write_blackbox, best-effort by contract.
        threading.Thread(target=self._write_blackbox_bg, args=(err,),
                         daemon=True,
                         name=f"rdt-serve-blackbox-{self.name}").start()

    def _write_blackbox_bg(self, err: BaseException) -> None:
        try:
            path = metrics.write_blackbox(f"serve-{self.name}", err)
            if path:
                logger.warning("serve request failed on every replica; "
                               "flight-recorder bundle written to %s", path)
        except Exception:  # noqa: BLE001 - never mask the request failure
            logger.warning("blackbox harvest for failed serve dispatch "
                           "failed", exc_info=True)

    def _note_replica_error(self, att: Optional[_Attempt],
                            err: BaseException) -> None:
        """Infra errors take the replica out of rotation and start a
        background reload; app errors (a bad request) leave it serving."""
        if att is None:
            return
        rep = att.replica
        not_loaded = (isinstance(err, RemoteError)
                      and err.exc_type == "ReplicaNotLoaded")
        if not (isinstance(err, ConnectionLost) or not_loaded):
            return
        if rep.reloading:
            return
        rep.ready = False
        rep.reloading = True
        metrics.record_event("replica_down", replica=rep.rid,
                             executor=rep.executor,
                             error=type(err).__name__)
        threading.Thread(target=self._reload, args=(rep,), daemon=True,
                         name=f"rdt-serve-reload-{rep.rid}").start()

    def _reload(self, rep: _ReplicaState) -> None:
        """Background: wait out the executor restart and reload the
        servable, then hand the replica back to the dispatcher. Reloads the
        replica's OWN bundle (``rep.export_dir``) — a canary replica must
        come back as the canary, not as whatever the primary moved to.
        Routed through the pool's live-member view: an executor that was
        RETIRED (drained out of the session) never comes back under its old
        handle, so the replica re-binds onto a surviving member and loads
        there — probing the corpse until the grace expired was exactly the
        fixed-identity bug this replaces."""
        deadline = time.monotonic() + self._reroute_grace_s
        last: Optional[BaseException] = None
        fails = 0
        while time.monotonic() < deadline:
            if self._closed:
                return  # session gone: stop dialing a stopped runtime
            try:
                replica = rep.replica
                replica.call("serve_load", rep.rid, rep.export_dir,
                             timeout=60.0)
                self._events.put(("replica_up", rep, None))
                return
            except Exception as e:  # noqa: BLE001 - keep probing the restart
                last = e
                fails += 1
                if self._maybe_rebind(rep, fails):
                    # fresh target: it earns its own probe allowance (a
                    # carried-over count would ping-pong the replica
                    # between live members on every failed probe)
                    fails = 0
                time.sleep(0.5)
        logger.error("replica %s did not come back within %.0fs: %s",
                     rep.rid, self._reroute_grace_s, last)
        self._events.put(("replica_up", rep, last))

    def _live_executors(self) -> List:
        """The owning session's current pool members (empty without one)."""
        if self._session is None:
            return []
        try:
            return [h for h in list(self._session.executors)
                    if getattr(h, "name", None)]
        except Exception:  # noqa: BLE001 - a stopping session reads as none
            return []

    def _all_replicas(self) -> List[_ReplicaState]:
        return [r for g in self._groups for r in g.replicas]

    def _maybe_rebind(self, rep: _ReplicaState, fails: int) -> bool:
        """Re-home a reloading replica whose executor left the pool: once
        the bound executor is no longer a live member (retired/reaped), or
        keeps refusing while live alternatives exist, bind the replica to
        the live member hosting the fewest replicas and let the reload loop
        land it there (True = the binding changed). The dispatcher reads
        ``rep.replica`` concurrently — a plain attribute swap, and either
        handle is safe to dial (a lost submit re-routes through the
        ordinary fault path)."""
        live = self._live_executors()
        if not live:
            return False
        names = {h.name for h in live}
        still_member = rep.executor in names
        # a live member may just be restarting in place: give it a few
        # probes before abandoning locality; a NON-member never returns
        if still_member and fails < 4:
            return False
        counts: Dict[str, int] = {}
        all_reps = self._all_replicas()
        for r in all_reps:
            counts[r.executor] = counts.get(r.executor, 0) + 1
        target = min(live, key=lambda h: (counts.get(h.name, 0)
                                          if h.name != rep.executor
                                          else len(all_reps) + 1))
        if target.name == rep.executor:
            return False
        logger.warning("replica %s re-homing from %s executor %s to %s",
                       rep.rid, "retired" if not still_member else "dead",
                       rep.executor, target.name)
        if still_member:
            # abandoning a LIVE member (persistent refusals, e.g. a long
            # GC pause): best-effort unload there, or a merely-unreachable
            # process would keep the rid's servable weights in RAM forever
            try:
                rep.replica.call("serve_unload", rep.rid, timeout=10.0)
            except Exception:  # noqa: BLE001 - it may really be dead
                pass
        rep.replica = target
        rep.executor = target.name
        return True

    def _on_replica_up(self, rep: _ReplicaState,
                       err: Optional[BaseException]) -> None:
        rep.reloading = False
        if err is None:
            rep.ready = True
            rep.reloads += 1
            rep.inflight = 0
            metrics.record_event("replica_up", replica=rep.rid,
                                 executor=rep.executor)
            logger.info("replica %s reloaded and back in rotation", rep.rid)

    # -- hot swap / version lifecycle (dispatcher side) -----------------------
    def _on_swap(self, new_reps: List[_ReplicaState], export_dir: str,
                 version: int, tag: Optional[str], done: Future) -> None:
        """The atomic half of :meth:`hot_swap`: one dispatcher step swaps
        the primary group, so a dispatch either chose the old version or
        the new one — never a mix, never a gap. Canary groups (if any)
        keep their weights and replicas."""
        old = self._primary
        group = _VersionGroup(version, export_dir, tag, new_reps,
                              weight=old.weight)
        self._groups[self._groups.index(old)] = group
        self._primary = group
        self.export_dir = export_dir
        self._swaps += 1
        self._retiring.append(
            (time.monotonic() + self._swap_drain_s, old.replicas,
             old.version))
        metrics.inc("serve_hot_swaps_total")
        metrics.record_event("hot_swap", session=self.name, version=version,
                             export_dir=export_dir, tag=tag or "")
        logger.info("serving session %s hot-swapped to v%d (%s%s); v%d "
                    "retiring behind %d in-flight dispatch(es)", self.name,
                    version, export_dir, f", tag={tag}" if tag else "",
                    old.version, sum(r.inflight for r in old.replicas))
        done.set_result({"version": version, "export_dir": export_dir,
                         "tag": tag,
                         "replicas": [r.rid for r in new_reps]})

    def _on_add_group(self, group: _VersionGroup, done: Future) -> None:
        self._groups.append(group)
        metrics.set_gauge("serve_version_weight", group.weight,
                          label=self._vlabel(group))
        metrics.set_gauge("serve_version_replicas", len(group.replicas),
                          label=self._vlabel(group))
        logger.info("serving session %s added v%d (%s) at weight %.3g "
                    "(%d replica(s))", self.name, group.version,
                    group.export_dir, group.weight, len(group.replicas))
        done.set_result({"version": group.version,
                         "export_dir": group.export_dir,
                         "tag": group.tag, "weight": group.weight,
                         "replicas": [r.rid for r in group.replicas]})

    def _on_set_weight(self, version: int, weight: float,
                       done: Future) -> None:
        g = self._group(version)
        if g is None:
            done.set_exception(ServingError(
                f"no live version v{version} in session {self.name!r}"))
            return
        g.weight = weight
        # fresh credit all around: the new split starts NOW, not after the
        # old credits drain through
        for grp in self._groups:
            grp.wrr = 0.0
        metrics.set_gauge("serve_version_weight", weight,
                          label=self._vlabel(g))
        done.set_result({"version": version, "weight": weight})

    def _on_promote(self, version: int, done: Future) -> None:
        g = self._group(version)
        if g is None:
            done.set_exception(ServingError(
                f"no live version v{version} to promote"))
            return
        if g is self._primary:
            done.set_result({"version": version, "already_primary": True})
            return
        old = self._primary
        self._groups.remove(old)
        g.weight = 1.0
        g.wrr = 0.0
        self._primary = g
        self.export_dir = g.export_dir
        self._swaps += 1
        self._retiring.append(
            (time.monotonic() + self._swap_drain_s, old.replicas,
             old.version))
        metrics.inc("serve_hot_swaps_total")
        metrics.set_gauge("serve_version_weight", 1.0, label=self._vlabel(g))
        metrics.set_gauge("serve_version_weight", 0.0,
                          label=self._vlabel(old))
        metrics.record_event("hot_swap", session=self.name,
                             version=g.version, export_dir=g.export_dir,
                             tag=g.tag or "", promoted=True)
        logger.info("serving session %s promoted v%d to primary; v%d "
                    "retiring behind %d in-flight dispatch(es)", self.name,
                    g.version, old.version,
                    sum(r.inflight for r in old.replicas))
        done.set_result({"version": g.version, "export_dir": g.export_dir,
                         "tag": g.tag, "retired": old.version})

    def _on_drop_group(self, version: int, done: Future) -> None:
        g = self._group(version)
        if g is None:
            done.set_exception(ServingError(
                f"no live version v{version} to drop"))
            return
        if g is self._primary:
            done.set_exception(ServingError(
                "cannot drop the primary version; promote another first"))
            return
        self._groups.remove(g)
        self._retiring.append(
            (time.monotonic() + self._swap_drain_s, g.replicas, g.version))
        metrics.set_gauge("serve_version_weight", 0.0,
                          label=self._vlabel(g))
        metrics.set_gauge("serve_version_replicas", 0,
                          label=self._vlabel(g))
        logger.info("serving session %s dropped v%d (%d replica(s) "
                    "retiring)", self.name, version, len(g.replicas))
        done.set_result({"version": version,
                         "requests": g.requests, "failed": g.failed,
                         "replicas": [r.rid for r in g.replicas]})

    def _on_add_replicas(self, version: int, reps: List[_ReplicaState],
                         rid_seq: int, done: Future) -> None:
        g = self._group(version)
        if g is None:
            # the group was dropped between the blocking load and this
            # step: retire the freshly loaded replicas instead of leaking
            self._retiring.append((time.monotonic(), reps, version))
            done.set_exception(ServingError(
                f"version v{version} disappeared during scale-up"))
            return
        g.replicas.extend(reps)
        g.rid_seq = max(g.rid_seq, rid_seq)
        metrics.set_gauge("serve_version_replicas", len(g.replicas),
                          label=self._vlabel(g))
        done.set_result({"version": version, "replicas": len(g.replicas),
                         "added": [r.rid for r in reps]})

    def _on_shrink_group(self, version: int, n: int, done: Future) -> None:
        g = self._group(version)
        if g is None:
            done.set_exception(ServingError(
                f"no live version v{version} to shrink"))
            return
        n = min(n, max(0, len(g.replicas) - 1))  # never below one replica
        # drain the least-busy first (ready replicas with work pending are
        # the ones actually carrying the load); not-ready replicas are the
        # cheapest victims of all
        victims = sorted(g.replicas,
                         key=lambda r: (r.ready, r.inflight))[:n]
        for r in victims:
            g.replicas.remove(r)
        if victims:
            self._retiring.append(
                (time.monotonic() + self._swap_drain_s, victims, version))
        metrics.set_gauge("serve_version_replicas", len(g.replicas),
                          label=self._vlabel(g))
        done.set_result({"version": version, "replicas": len(g.replicas),
                         "removed": [r.rid for r in victims]})

    def _retire_swapped(self) -> None:
        """Unload swapped-out versions (and scaled-down replicas) once
        their in-flight dispatches drained (or the ``RDT_SERVE_SWAP_DRAIN_S``
        deadline passed — the straggler requests still complete; only the
        registry entry goes)."""
        if not self._retiring:
            return
        keep = []
        for deadline, reps, ver in self._retiring:
            if all(r.inflight <= 0 for r in reps) \
                    or time.monotonic() >= deadline:
                # the unloads are RPCs with their own timeouts: background
                # thread, never the dispatcher loop
                threading.Thread(
                    target=self._unload_replicas, args=(reps, ver),
                    daemon=True,
                    name=f"rdt-serve-retire-{self.name}-v{ver}").start()
            else:
                keep.append((deadline, reps, ver))
        self._retiring = keep

    def _unload_replicas(self, reps: List[_ReplicaState], ver: int) -> None:
        """Unload retired replicas, RETRIED through the reload-probe shape:
        an executor mid-restart refuses now but answers within the grace,
        so fire-and-forget here used to leave the servable's weights pinned
        in the restarted process's RAM forever. An executor that left the
        pool entirely (retired member) took the registry down with its
        process — that counts as unloaded. A replica that still refuses at
        the deadline is counted LOUDLY (``serve_unload_failed_total`` + an
        ``unload_failed`` event) instead of silently leaking."""
        deadline = time.monotonic() + self._reroute_grace_s
        failed = 0
        for rep in reps:
            last: Optional[BaseException] = None
            while True:
                try:
                    rep.replica.call("serve_unload", rep.rid, timeout=10.0)
                    last = None
                    break
                except Exception as e:  # noqa: BLE001 - probe the restart
                    last = e
                    live = self._live_executors()
                    if live and rep.executor not in {h.name for h in live}:
                        # the executor is out of the pool: its process (and
                        # the replica registry pinning the weights) is gone
                        last = None
                        break
                    if self._closed or time.monotonic() >= deadline:
                        break
                    time.sleep(0.5)
            if last is not None:
                failed += 1
                metrics.inc("serve_unload_failed_total")
                metrics.record_event("unload_failed", session=self.name,
                                     replica=rep.rid, executor=rep.executor,
                                     version=ver, error=str(last)[:200])
                logger.error(
                    "replica %s (v%d) refused serve_unload on %s within "
                    "%.0fs — its servable weights stay pinned in that "
                    "process: %s", rep.rid, ver, rep.executor,
                    self._reroute_grace_s, last)
        logger.info("serving session %s retired servable v%d "
                    "(%d/%d replica(s) unloaded)", self.name, ver,
                    len(reps) - failed, len(reps))

    # -- hedging --------------------------------------------------------------
    def _hedge_deadline(self) -> Optional[float]:
        """Seconds after which an in-flight dispatch earns a hedge, or None
        while hedging is off / unwarmed / pointless (no version group holds
        a second replica to race)."""
        if not self._hedge_on \
                or not any(len(g.replicas) >= 2 for g in self._groups):
            return None
        if len(self._batch_lat) < _HEDGE_MIN_SAMPLES:
            return None
        return max(self._hedge_mult * _quantile(self._batch_lat,
                                                self._hedge_q),
                   self._hedge_min_s)

    def _maybe_hedge(self) -> None:
        if self._shedding():
            return  # hedges amplify overload; suppressed while saturated
        deadline = self._hedge_deadline()
        if deadline is None:
            return
        now = time.monotonic()
        for d in list(self._inflight.values()):
            if d.done or d.hedged or not d.attempts:
                continue
            # hedges are VERSION-LOCAL: the duplicate races a sibling of
            # the same servable, so a canary never answers a baseline
            # request (and vice versa) through the hedge path
            g = self._group(d.version)
            if g is None or len(g.replicas) < 2:
                continue
            if now - d.t_first >= deadline:
                # count (and retire) the hedge only once it is really in
                # flight: with the sibling replica reloading/at-fault the
                # dispatch stays eligible and retries on a later tick
                if self._submit(d, hedge=True):
                    d.hedged = True
                    self._stats["hedged"] += 1
                    metrics.inc("serve_hedged_total")
                    metrics.record_event("hedge", dispatch=d.id,
                                         rows=d.rows)

    # -- reporting / teardown -------------------------------------------------
    def _report(self) -> Dict[str, Any]:
        lat = sorted(self._req_lat)
        occ = self._occupancy
        out = dict(self._stats)
        with self._adm_lock:
            shed = self._shed_count
            outstanding = self._outstanding
        # a shed request IS a failed request from the caller's view, so
        # ``failed`` includes ``shed`` — a clean overload run reads
        # failed == shed (nothing failed except typed rejections)
        out["shed"] = shed
        out["failed"] = out["failed"] + shed
        primary = self._primary
        replica_rows = []
        version_rows = []
        for g in sorted(self._groups,
                        key=lambda x: (x is not primary, x.version)):
            glat = sorted(g.req_lat)
            version_rows.append({
                "version": g.version,
                "export_dir": g.export_dir,
                "tag": g.tag,
                "weight": g.weight,
                "primary": g is primary,
                "requests": g.requests,
                "failed": g.failed,
                # admission sheds precede version choice (no dispatch
                # exists yet to attribute): charged to the primary, whose
                # saturation they are
                "shed": shed if g is primary else 0,
                "p50_ms": round(_quantile(glat, 0.50) * 1000.0, 3),
                "p99_ms": round(_quantile(glat, 0.99) * 1000.0, 3),
                "lat_n": len(glat),
                "replicas": len(g.replicas),
                "ready": sum(1 for r in g.replicas if r.ready),
            })
            for r in g.replicas:
                replica_rows.append({
                    "replica": r.rid,
                    "version": g.version,
                    "executor": r.executor,
                    "ready": r.ready,
                    "requests": r.requests,
                    "batches": r.batches,
                    "rows": r.rows,
                    "hedges": r.hedges,
                    "inflight": r.inflight,
                    "inflight_peak": r.inflight_peak,
                    "reloads": r.reloads,
                })
        out.update({
            # which model answers the PRIMARY traffic right now: the active
            # servable's version, bundle dir, and the tag the swapper
            # attached (partial_fit's source epoch) — what the bench/chaos
            # legs assert on
            "servable": {"version": primary.version,
                         "export_dir": primary.export_dir,
                         "tag": primary.tag},
            "hot_swaps": self._swaps,
            "versions": version_rows,
            "retiring_replicas": sum(len(reps)
                                     for _, reps, _ in self._retiring),
            "outstanding": outstanding,
            "max_queue": self._max_queue,
            "max_inflight": self._max_inflight,
            "shedding": self._max_queue > 0 and outstanding >= self._max_queue,
            "p50_ms": round(_quantile(lat, 0.50) * 1000.0, 3),
            "p99_ms": round(_quantile(lat, 0.99) * 1000.0, 3),
            "mean_batch_occupancy": (round(sum(occ) / len(occ), 2)
                                     if occ else 0.0),
            "max_batch_occupancy": max(occ) if occ else 0,
            "queue_depth": len(self._pending) + len(self._inflight),
            "queue_depth_peak": self._queue_depth_peak,
            "replicas": replica_rows,
        })
        return out

    def _drain_stop(self) -> None:
        err = ServingError("serving session closed with requests in flight")
        for req in self._pending:
            if not req.fut.done():
                req.fut.set_exception(err)
            req.finish(failed=True)
        self._pending = []
        for d in list(self._inflight.values()) + self._parked:
            if not d.done:
                for req, _ in d.parts:
                    if not req.fut.done():
                        req.fut.set_exception(err)
                    req.finish(failed=True)
        self._inflight.clear()
        self._parked = []
        # requests enqueued behind the stop event would otherwise hold
        # futures nobody ever completes
        while True:
            try:
                ev = self._events.get_nowait()
            except queue.Empty:
                break
            if ev[0] == "req":
                if not ev[1].fut.done():
                    ev[1].fut.set_exception(err)
                ev[1].finish(failed=True)
            elif ev[0] in ("swap_prep", "scale_prep"):
                if not ev[1].done():
                    ev[1].set_exception(
                        ServingError("serving session closed mid-swap"))
            elif ev[0] in ("swap", "add_group", "add_replicas"):
                # the new version/replicas DID load on the executors:
                # unload them (in the background — these are RPCs) instead
                # of leaving their weights pinned in executor RAM forever
                if ev[0] == "swap":
                    reps, ver, done = ev[1], ev[3], ev[5]
                elif ev[0] == "add_group":
                    reps, ver, done = ev[1].replicas, ev[1].version, ev[2]
                else:
                    reps, ver, done = ev[2], ev[1], ev[4]
                threading.Thread(
                    target=self._unload_replicas, args=(reps, ver),
                    daemon=True,
                    name=f"rdt-serve-drainswap-{self.name}").start()
                if not done.done():
                    done.set_exception(
                        ServingError("serving session closed mid-swap"))
            elif ev[0] in ("set_weight", "promote", "drop_group",
                           "shrink_group"):
                done = ev[-1]
                if not done.done():
                    done.set_exception(
                        ServingError("serving session closed"))
            elif ev[0] == "report":
                ev[1].set_result(self._report())
