"""Gang-scheduled SPMD job subsystem — the reference's MPI pillar, TPU-native.

The reference runs arbitrary MPI programs on its cluster: a gRPC control plane
broadcasts cloudpickled functions to mpirun-launched ranks and gathers results
(reference: python/raydp/mpi/__init__.py:36-91, mpi_job.py:165-338,
mpi_worker.py:144-214). Here the external process gang is a JAX process group:
one process per host (per chip-set), meshed by ``jax.distributed.initialize``
— the coordinator service replaces mpirun's wire-up, and in-program collectives
are XLA collectives over ICI/DCN instead of MPI.

    job = create_spmd_job("train", world_size=4)
    job.start()
    results = job.run(lambda ctx: ctx.rank * 2)
    job.stop()
"""

from raydp_tpu.spmd.job import SPMDJob, WorkerContext, create_spmd_job

__all__ = ["create_spmd_job", "SPMDJob", "WorkerContext"]
