"""Driver side of the gang-SPMD job runner.

Parity map (reference → here):

- ``MPIJob.start`` — gRPC DriverService + STRICT_SPREAD placement group +
  ``mpirun`` spawn + two-phase registration barrier
  (mpi_job.py:165-318) → an RPC driver service, a placement group over the
  runtime's nodes, a direct gang spawn of rank processes, and the same
  two-phase barrier (register → start worker service → register service).
- ``MPIJob.run(fn)`` — cloudpickle broadcast + world-size result gather
  (mpi_job.py:324-338) → synchronous fan-out over per-rank RPC stubs with
  in-order ``func_id`` sequencing enforced worker-side (mpi_worker.py:75-96).
- ``OpenMPIJob``/``IntelMPIJob``/``MPICHJob`` mpirun-flag variants
  (mpi_job.py:411-429) → ``jax_distributed=True`` wires a JAX coordinator
  (rank 0) so ranks form one global device mesh; ``False`` runs plain Python
  ranks (still gang-placed, still object-store-connected).
- each MPI rank also joins Ray (mpi_worker.py:159-160) → each rank inherits
  the head address + session env and connects an object-store client, so SPMD
  programs can read/write the Arrow data plane.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from concurrent.futures import Future, InvalidStateError

from raydp_tpu.log import get_logger
from raydp_tpu.runtime.rpc import (
    DeferredReply, MethodDispatcher, RpcClient, RpcServer)

logger = get_logger("spmd")

ENV_JOB_ID = "RDT_SPMD_JOB_ID"
ENV_DRIVER = "RDT_SPMD_DRIVER"
ENV_RANK = "RDT_SPMD_RANK"
ENV_WORLD = "RDT_SPMD_WORLD_SIZE"
ENV_COORDINATOR = "RDT_SPMD_COORDINATOR"
ENV_JAX_DIST = "RDT_SPMD_JAX_DISTRIBUTED"


@dataclass
class WorkerContext:
    """Handed to the user function on every rank (parity: mpi_worker.py
    ``WorkerContext`` — job name, rank, world size)."""

    job_id: str
    rank: int
    world_size: int

    def __repr__(self):
        return f"WorkerContext(job={self.job_id}, rank={self.rank}/{self.world_size})"


class _DriverService:
    """Registration + liveness endpoint the ranks call into
    (parity: DriverService in mpi/network/network.proto:22-30)."""

    def __init__(self, job: "SPMDJob"):
        self._job = job

    def register_worker(self, rank: int, pid: int) -> Dict[str, Any]:
        return self._job._on_register_worker(rank, pid)

    def register_worker_service(self, rank: int, host: str, port: int) -> bool:
        return self._job._on_register_service(rank, host, port)

    def set_coordinator(self, address: str) -> bool:
        return self._job._on_set_coordinator(address)

    def get_coordinator(self, timeout: float = 120.0):
        # DeferredReply-based: every non-zero rank long-polls here while
        # rank 0 is still importing jax — parking dispatchers on a condition
        # wait would make set_coordinator queue behind the very waiters it
        # must wake (pool exhaustion; rdtlint dispatcher-blocking)
        return self._job._coordinator_reply(timeout)

    def ping(self) -> str:
        return "pong"


class SPMDJob:
    """A restartable gang of SPMD rank processes under one control plane.

    ``start()`` → ``run(fn)``×N → ``stop()``; the same object can be started
    again after ``stop()`` (the reference's test restarts a job object,
    test_mpi.py start/run/stop/restart case).
    """

    def __init__(
        self,
        job_name: str,
        world_size: int,
        env: Optional[Dict[str, str]] = None,
        jax_distributed: bool = False,
        placement_strategy: str = "SPREAD",
        cpus_per_process: float = 1.0,
        timeout: float = 120.0,
    ):
        self.job_name = job_name
        self.world_size = world_size
        self.extra_env = dict(env or {})
        self.jax_distributed = jax_distributed
        self.placement_strategy = placement_strategy
        self.cpus_per_process = cpus_per_process
        self.timeout = timeout

        self._server: Optional[RpcServer] = None
        self._procs: List[subprocess.Popen] = []
        self._stubs: Dict[int, RpcClient] = {}
        self._registered: Dict[int, int] = {}
        self._services: Dict[int, tuple] = {}
        self._barrier = threading.Condition()
        self._func_id = 0
        self._started = False
        self._placement_group_id: Optional[str] = None
        self._coordinator: Optional[str] = None
        #: get_coordinator long-polls parked as futures — dispatcher threads
        #: return immediately; each waiter holds one short-lived daemon
        #: Timer for its deadline (gang-sized, never dispatcher-pool-sized)
        self._coord_waiters: List[Future] = []

    # -- registration callbacks (driver service) ------------------------------
    def _on_register_worker(self, rank: int, pid: int) -> Dict[str, Any]:
        with self._barrier:
            self._registered[rank] = pid
            self._barrier.notify_all()
        return {"job_id": self.job_name, "world_size": self.world_size}

    def _on_register_service(self, rank: int, host: str, port: int) -> bool:
        with self._barrier:
            self._services[rank] = (host, port)
            self._barrier.notify_all()
        return True

    def _on_set_coordinator(self, address: str) -> bool:
        """Rank 0 picks the JAX coordinator port on its own interface moments
        before ``jax.distributed`` binds it and reports it here — a far
        smaller reuse window than a driver-side pick that sits unclaimed
        through the whole gang spawn (and a gang restart retries it). The
        host is rank 0's routable address, so the gang is not limited to one
        machine."""
        with self._barrier:
            self._coordinator = address
            waiters, self._coord_waiters = self._coord_waiters, []
            self._barrier.notify_all()
        # complete OUTSIDE the lock: a done-callback (the RPC server's reply
        # submit) must never run under it
        for fut in waiters:
            try:
                fut.set_result(address)
            except InvalidStateError:
                pass  # lost the race to this waiter's timeout timer
        return True

    def _coordinator_reply(self, timeout: float):
        """The coordinator address immediately when known, else a
        :class:`~raydp_tpu.runtime.rpc.DeferredReply` completed by rank 0's
        ``set_coordinator`` (or failed at ``timeout``). Replaces a condition
        wait that parked one dispatcher PER WAITING RANK: with the pool
        sized below ``world_size - 1`` the ``set_coordinator`` call that
        wakes the waiters would queue behind them — deadlock until every
        waiter timed out."""
        with self._barrier:
            if self._coordinator is not None:
                return self._coordinator
            fut: Future = Future()
            self._coord_waiters.append(fut)
        timer = threading.Timer(timeout, self._coord_timeout, args=(fut,))
        timer.daemon = True
        timer.start()
        fut.add_done_callback(lambda _f: timer.cancel())
        return DeferredReply(fut)

    def _coord_timeout(self, fut: "Future") -> None:
        # claim the waiter under the lock: set_coordinator/_reset swap the
        # list out BEFORE completing futures, so a fut no longer listed is
        # theirs to complete — failing it here would turn a coordinator
        # that arrived exactly at the deadline into a spurious timeout
        with self._barrier:
            claimed = fut in self._coord_waiters
            if claimed:
                self._coord_waiters.remove(fut)
        if not claimed:
            return
        try:
            fut.set_exception(TimeoutError(
                "coordinator address never arrived "
                "(rank 0 dead before jax.distributed?)"))
        except InvalidStateError:
            pass  # completed while we were between lock and here

    def _wait_barrier(self, table: dict, phase: str) -> None:
        deadline = time.time() + self.timeout
        with self._barrier:
            while len(table) < self.world_size:
                remaining = deadline - time.time()
                if remaining <= 0 or not self._barrier.wait(timeout=min(1.0, remaining)):
                    self._check_procs_alive()
                if time.time() >= deadline and len(table) < self.world_size:
                    raise TimeoutError(
                        f"SPMD job {self.job_name}: {phase} barrier timed out "
                        f"({len(table)}/{self.world_size} ranks)")

    def _check_procs_alive(self) -> None:
        for i, p in enumerate(self._procs):
            code = p.poll()
            if code is not None and code != 0:
                raise RuntimeError(
                    f"SPMD job {self.job_name}: rank {i} exited with code "
                    f"{code} during startup (see {self._log_path(i)})")

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "SPMDJob":
        if self._started:
            raise RuntimeError(f"SPMD job {self.job_name} already started")
        # a restarted gang's rank 0 binds a FRESH coordinator port; serving
        # the previous gang's address would wedge every other rank's
        # jax.distributed.initialize against a dead socket
        self._coordinator = None
        self._reserve_placement()
        self._server = RpcServer(MethodDispatcher(_DriverService(self)),
                                 max_concurrency=max(4, self.world_size),
                                 name=f"spmd-{self.job_name}")
        for rank in range(self.world_size):
            self._procs.append(self._spawn_rank(rank))
        # two-phase barrier (parity: mpi_job.py:280-318)
        self._wait_barrier(self._registered, "register")
        self._wait_barrier(self._services, "service")
        for rank, addr in sorted(self._services.items()):
            self._stubs[rank] = RpcClient(addr)
        self._started = True
        logger.info("SPMD job %s started: %d ranks%s", self.job_name,
                    self.world_size,
                    " (jax.distributed mesh)" if self.jax_distributed else "")
        return self

    def _reserve_placement(self) -> None:
        """Gang-reserve CPU bundles through the runtime when one is live
        (parity: STRICT_SPREAD pg pinning nodes, mpi_job.py:192-222); a bare
        job without a runtime still works — it is just unaccounted."""
        from raydp_tpu.runtime import head as head_mod

        if not head_mod.runtime_initialized():
            return
        rt = head_mod.get_runtime()
        bundles = [{"CPU": self.cpus_per_process}
                   for _ in range(self.world_size)]
        from raydp_tpu.runtime.placement import PlacementStrategy
        group = rt.resource_manager.create_group(
            bundles, PlacementStrategy(self.placement_strategy.upper()))
        self._placement_group_id = group.group_id

    def _log_path(self, rank: int) -> str:
        from raydp_tpu.runtime import head as head_mod

        if head_mod.runtime_initialized():
            base = os.path.join(head_mod.get_runtime().session_dir, "logs")
        else:
            base = "/tmp/raydp_tpu/spmd"
        os.makedirs(base, exist_ok=True)
        return os.path.join(base, f"spmd-{self.job_name}-rank{rank}.out")

    def _rank_agent(self, rank: int):
        """(agent client, node) serving this rank's placement bundle, when the
        bundle landed on a node-agent machine — gang ranks then spawn there,
        one process per host, the way `mpirun -hosts` fans ranks out
        (mpi_job.py:240-278)."""
        from raydp_tpu.runtime import head as head_mod

        if self._placement_group_id is None or not head_mod.runtime_initialized():
            return None, None
        rt = head_mod.get_runtime()
        group = rt.resource_manager.get_group(self._placement_group_id)
        if group is None or rank >= len(group.bundles):
            return None, None
        node_id = group.bundle_node(rank)
        agent = rt.node_agents.get(node_id) if node_id else None
        node = rt.resource_manager.get_node(node_id) if node_id else None
        return agent, node

    def _spawn_rank(self, rank: int):
        # an override valued None means "remove from the child env" (e.g.
        # dropping a TPU-plugin discovery var so CPU-pinned ranks cannot touch
        # a tunnel) — honored by both the local spawn below and NodeAgent.spawn
        env_overrides: Dict[str, str] = dict(self.extra_env)
        from raydp_tpu.runtime import head as head_mod
        rt = None
        if head_mod.runtime_initialized():
            # hand ranks the session so they join the data plane
            # (parity: ray.init in every MPI rank, mpi_worker.py:159-160)
            rt = head_mod.get_runtime()
            env_overrides[head_mod.ENV_HEAD] = rt.server.url
            env_overrides[head_mod.ENV_SESSION] = rt.session_id
            env_overrides[head_mod.ENV_SESSION_DIR] = rt.session_dir
        env_overrides[ENV_JOB_ID] = self.job_name
        env_overrides[ENV_DRIVER] = self._server.url
        env_overrides[ENV_RANK] = str(rank)
        env_overrides[ENV_WORLD] = str(self.world_size)
        env_overrides[ENV_JAX_DIST] = "1" if self.jax_distributed else "0"
        driver_path = [p for p in sys.path if p]
        if env_overrides.get("PYTHONPATH"):  # user extra_env path first
            driver_path.insert(0, env_overrides["PYTHONPATH"])
        if os.environ.get("PYTHONPATH"):
            driver_path.append(os.environ["PYTHONPATH"])
        env_overrides["PYTHONPATH"] = os.pathsep.join(driver_path)

        agent, node = self._rank_agent(rank)
        if agent is not None:
            # None-valued overrides ride through: the agent applies them as
            # removals in the child env (NodeAgent.spawn). Data-plane env
            # (RDT_STORE_HOST_ID / PAYLOAD_ADDR / ARENA) is injected by the
            # agent itself when its machine hosts an isolated payload plane.
            pid = agent.call("spawn", env_overrides,
                             f"spmd-{self.job_name}-rank{rank}",
                             ["-u", "-m", "raydp_tpu.spmd.worker"],
                             timeout=30.0)
            from raydp_tpu.runtime.head import _RemoteProcess
            return _RemoteProcess(agent, pid, node.node_id if node else "")

        env = dict(os.environ)
        for k, v in env_overrides.items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = v
        out = open(self._log_path(rank), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-u", "-m", "raydp_tpu.spmd.worker"],
            env=env, stdout=out, stderr=subprocess.STDOUT,
            start_new_session=True)
        out.close()
        return proc

    # -- execution ------------------------------------------------------------
    def run(self, fn: Callable[[WorkerContext], Any],
            timeout: Optional[float] = None) -> List[Any]:
        """Broadcast ``fn`` to every rank; return world-size results ordered by
        rank (parity: mpi_job.py:324-338)."""
        if not self._started:
            raise RuntimeError(f"SPMD job {self.job_name} not started")
        import concurrent.futures as cf

        self._func_id += 1
        payload = cloudpickle.dumps(fn)
        fut_to_rank = {
            stub.submit("run_function", self._func_id, payload): rank
            for rank, stub in self._stubs.items()
        }
        results: List[Any] = [None] * self.world_size
        # fail fast: a dead rank surfaces the moment its connection drops,
        # without waiting out ranks that are hung in a collective behind it
        for fut in cf.as_completed(fut_to_rank, timeout=timeout or self.timeout):
            rank = fut_to_rank[fut]
            ok, value = fut.result()
            if not ok:
                raise RuntimeError(
                    f"SPMD job {self.job_name} rank {rank} failed:\n{value}")
            results[rank] = value
        return results

    def rank_addresses(self) -> Dict[int, tuple]:
        """Rank → worker-service address (parity: the reference exposes
        worker addresses for tests, test_mpi.py rank-address query)."""
        return dict(self._services)

    def stop(self) -> None:
        for rank, stub in list(self._stubs.items()):
            try:
                stub.submit("stop")
            except Exception:
                pass
        deadline = time.time() + 5.0
        from raydp_tpu.runtime.head import _RemoteProcess
        for p in self._procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                if isinstance(p, _RemoteProcess):
                    p.kill()
                    continue
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    try:
                        p.kill()
                    except ProcessLookupError:
                        pass
        self._reset()

    def _reset(self) -> None:
        """Full teardown so the same job object can start again
        (parity: mpi_job.py:344-395 ``_reset``)."""
        with self._barrier:
            waiters, self._coord_waiters = self._coord_waiters, []
        for fut in waiters:  # a parked get_coordinator must not outlive us
            try:
                fut.set_exception(TimeoutError(
                    f"SPMD job {self.job_name} stopped before rank 0 "
                    "reported a coordinator"))
            except InvalidStateError:
                pass  # its timeout timer already failed it
        for stub in self._stubs.values():
            stub.close()
        self._stubs.clear()
        if self._server is not None:
            self._server.stop()
            self._server = None
        if self._placement_group_id is not None:
            from raydp_tpu.runtime import head as head_mod
            if head_mod.runtime_initialized():
                try:
                    head_mod.get_runtime().resource_manager.remove_group(
                        self._placement_group_id)
                except Exception:
                    pass
            self._placement_group_id = None
        self._procs.clear()
        self._registered.clear()
        self._services.clear()
        self._coordinator = None
        self._func_id = 0
        self._started = False
        logger.info("SPMD job %s stopped", self.job_name)


def create_spmd_job(
    job_name: str,
    world_size: int,
    env: Optional[Dict[str, str]] = None,
    jax_distributed: bool = False,
    placement_strategy: str = "SPREAD",
    cpus_per_process: float = 1.0,
    timeout: float = 120.0,
) -> SPMDJob:
    """Factory, shape-parity with ``raydp.mpi.create_mpi_job``
    (mpi/__init__.py:36-91)."""
    return SPMDJob(job_name=job_name, world_size=world_size, env=env,
                   jax_distributed=jax_distributed,
                   placement_strategy=placement_strategy,
                   cpus_per_process=cpus_per_process, timeout=timeout)


def _free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port
