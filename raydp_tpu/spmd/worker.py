"""Rank-process entry point (``python -m raydp_tpu.spmd.worker``).

Parity: ``mpi_worker.py`` — rank from env (33-42), two-phase registration to the
driver (144-166), in-order function execution with ``func_id`` sequencing
(63-96), and joining the data plane the way each MPI rank re-joins Ray
(159-160): if this process inherited a runtime head address it connects an
object-store client before serving functions.

When ``RDT_SPMD_JAX_DISTRIBUTED=1`` the rank calls
``jax.distributed.initialize`` against the job coordinator before serving, so
user functions run inside one global JAX process group — collectives are XLA
collectives over the global device mesh, the TPU-native replacement for the
reference's in-rank MPI calls.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

import cloudpickle

from raydp_tpu import knobs
from raydp_tpu.log import init_logging
from raydp_tpu.runtime.rpc import RpcServer, connect_with_retry
from raydp_tpu.spmd.job import (
    ENV_COORDINATOR, ENV_DRIVER, ENV_JAX_DIST, ENV_JOB_ID, ENV_RANK, ENV_WORLD,
    WorkerContext, _free_port,
)


class _WorkerService:
    """Serves RunFunction/Stop (parity: WorkerService, network.proto:32-37)."""

    def __init__(self, ctx: WorkerContext):
        self._ctx = ctx
        self._last_func_id = 0
        self._lock = threading.Lock()

    def __call__(self, method: str, args: tuple, kwargs: dict):
        if method == "run_function":
            return self._run_function(*args)
        if method == "stop":
            threading.Thread(target=_delayed_exit, daemon=True).start()
            return True
        if method == "ping":
            return "pong"
        raise AttributeError(f"unknown worker method {method!r}")

    def _run_function(self, func_id: int, payload: bytes):
        with self._lock:  # functions run one at a time, in order
            if func_id != self._last_func_id + 1:
                return False, (f"out-of-order function: got {func_id}, "
                               f"expected {self._last_func_id + 1}")
            fn = cloudpickle.loads(payload)
            try:
                value = fn(self._ctx)
                self._last_func_id = func_id
                return True, value
            except BaseException:  # noqa: BLE001 - report any failure to driver
                self._last_func_id = func_id
                return False, traceback.format_exc()


def _delayed_exit():
    time.sleep(0.2)
    os._exit(0)


def main() -> None:
    import faulthandler
    import signal

    # SIGUSR1 → dump all thread stacks to stderr (lands in the rank .out
    # file), so a hung collective can be diagnosed from outside
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    job_id = str(knobs.require(ENV_JOB_ID))
    driver_url = str(knobs.require(ENV_DRIVER))
    rank = int(knobs.require(ENV_RANK))
    world_size = int(knobs.require(ENV_WORLD))

    init_logging(f"spmd-{job_id}-r{rank}", str(knobs.get("RDT_LOG_LEVEL")),
                 None, job_id)

    d_host, d_port = driver_url.rsplit(":", 1)
    driver = connect_with_retry((d_host, int(d_port)))
    reply = driver.call("register_worker", rank, os.getpid())
    assert reply["world_size"] == world_size

    if knobs.get(ENV_JAX_DIST):
        import jax
        # interpreter startup may have pre-registered a hardware platform;
        # backend init is lazy, so re-assert the requested platform before
        # the first device touch (same dance as tests/conftest.py)
        if os.environ.get("JAX_PLATFORMS"):
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        coordinator = knobs.get(ENV_COORDINATOR)  # test/ops override
        if not coordinator:
            if rank == 0:
                # rank 0 picks the port on its own routable interface moments
                # before jax binds it (narrows the reuse race to this process's
                # own window — a driver-side pick could sit unclaimed through
                # the whole gang spawn) and reports it to the other ranks via
                # the driver; the host is this process's address toward the
                # driver, reachable from peers on other machines
                host = driver.local_host
                coordinator = f"{host}:{_free_port(host)}"
                driver.call("set_coordinator", coordinator)
            else:
                # first arg is the server-side wait; the kwarg is the client
                # deadline (RpcClient.call consumes `timeout=` itself)
                coordinator = driver.call("get_coordinator", 120.0,
                                          timeout=130.0)
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=world_size, process_id=rank)

    # join the data plane if a runtime session is live (parity: ray.init in
    # every MPI rank, mpi_worker.py:159-160)
    from raydp_tpu.runtime import head as head_mod
    from raydp_tpu.runtime import object_store as objstore
    from raydp_tpu.runtime.actor_main import StoreTableProxy

    head_url = os.environ.get(head_mod.ENV_HEAD)
    session_id = os.environ.get(head_mod.ENV_SESSION)
    if head_url and session_id:
        host, port = head_url.rsplit(":", 1)
        try:
            head_client = connect_with_retry((host, int(port)))
            store = objstore.ObjectStoreClient(
                StoreTableProxy(head_client), session_id,
                default_owner=f"spmd-{job_id}-r{rank}")
            objstore.set_client(store)
        except Exception as e:
            import logging
            logging.getLogger("raydp_tpu").warning(
                "rank %d could not join the object store at %s: %s "
                "(functions needing the data plane will fail)",
                rank, head_url, e)

    ctx = WorkerContext(job_id=job_id, rank=rank, world_size=world_size)

    server = RpcServer(_WorkerService(ctx), host=driver.local_host, port=0,
                       max_concurrency=2, name=f"spmd-r{rank}")
    driver.call("register_worker_service", rank, server.address[0],
                server.address[1])

    # die with the driver (parity: mpirun teardown kills ranks; here the rank
    # watches the control connection)
    try:
        while True:
            driver.call("ping", timeout=30.0)
            time.sleep(5.0)
    except Exception:
        pass
    finally:
        server.stop()
        os._exit(0)


if __name__ == "__main__":
    main()
