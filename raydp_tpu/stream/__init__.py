"""Continuous pipelines: streaming ingest → incremental shuffle epochs →
windowed aggregation → online training → serving hot-swap (doc/streaming.md).

    from raydp_tpu import stream
    pipe = stream.read_stream(stream.FileTailSource("/landing")) \
               .transform(lambda df: df.filter(...)) \
               .window(size=4, keys=["k"], aggs={"v": ["sum", "mean"]})
    for epoch in pipe.epochs():
        ...
"""

from raydp_tpu.stream.pipeline import (
    ContinuousPipeline,
    EpochResult,
    EpochStream,
    WindowResult,
    read_stream,
)
from raydp_tpu.stream.sources import (
    FileTailSource,
    MicroBatch,
    ReplayLogSource,
    StreamError,
    StreamSource,
    SyntheticSource,
)

__all__ = [
    "ContinuousPipeline",
    "EpochResult",
    "EpochStream",
    "FileTailSource",
    "MicroBatch",
    "ReplayLogSource",
    "StreamError",
    "StreamSource",
    "SyntheticSource",
    "WindowResult",
    "read_stream",
]
