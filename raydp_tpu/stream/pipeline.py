"""Continuous pipelines: micro-batched epochs over the batch ETL engine.

:func:`read_stream` turns a :class:`~raydp_tpu.stream.sources.StreamSource`
into a :class:`ContinuousPipeline`. Each source micro-batch runs as one
**incremental shuffle epoch**: the batch becomes an in-store frame, the
pipeline's ``transform`` (the full DataFrame surface — filter/project/
groupagg/join against static or broadcast sides) runs as an ordinary engine
action (AQE, pipelined shuffle, speculation, lineage recovery and the
abort/no-orphan contract all apply inside the epoch), and the epoch's
result seals into the object store as one Arrow blob **published through
the PR 7 ShuffleStreamLedger** (stage key = the pipeline id, map id = the
epoch id) — downstream consumers (:meth:`ContinuousPipeline.epoch_stream`,
``EstimatorInterface.partial_fit``) long-poll the ledger and ranged-fetch
each epoch as its seal lands, exactly like a pipelined shuffle's reducers.

**Windowed aggregations** (tumbling/sliding over epoch ids) carry stateful
partials across epochs *via the store*: every epoch materializes a partial
aggregate (decomposable ops — sum/count/min/max/mean) whose refs persist
until every window containing the epoch has closed; a closing window merges
its partials with one more engine action.

**Exactly-once.** A lost epoch blob (``ObjectLostError`` — host died, spill
file lost, chaos ``stream.epoch:drop``) is replayed through the source's
deterministic journal: the pipeline re-derives the epoch's rows, re-runs
the same transform/partial action, and re-seals — window merges retry over
the replayed partials, and a re-sealed epoch RESULT publishes under
``gen+1`` so in-flight consumers discard and refetch (the ledger's
generation semantics). Replays are byte-identical to the original epoch, so
a chaos run's window results match an unfaulted run exactly, with every
epoch contributing exactly once.

Driver threads only — nothing here runs on an RPC dispatcher.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import pyarrow as pa

from raydp_tpu import faults, knobs, metrics, profiler
from raydp_tpu.log import get_logger
from raydp_tpu.runtime.object_store import (
    KIND_ARROW,
    ObjectLostError,
    ObjectRef,
    get_client,
)
from raydp_tpu.stream.sources import StreamError, StreamSource

logger = get_logger("stream.pipeline")

#: decomposable window ops: per-epoch partial column -> merge op
_WINDOW_OPS = ("sum", "count", "min", "max", "mean")


@dataclass(frozen=True)
class WindowResult:
    """One closed window: epochs ``[start, end]`` inclusive, rows sorted by
    the window keys (groupagg row order is otherwise unspecified)."""

    start: int
    end: int
    table: pa.Table


@dataclass
class EpochResult:
    """One completed epoch: the sealed result blob + any windows that
    closed at this epoch."""

    epoch: int
    input_rows: int
    ref: ObjectRef          # the sealed epoch-result blob (ledger-published)
    num_rows: int           # rows in the result blob
    wall_s: float
    schema: Optional[pa.Schema] = None   # captured at seal time
    windows: List[WindowResult] = field(default_factory=list)

    def table(self) -> pa.Table:
        return get_client().get(self.ref)

    def dataset(self):
        """The epoch result as a 1-block dataset for the feed plane."""
        from raydp_tpu.data.dataset import BlockMeta, DistributedDataset
        schema = self.schema if self.schema is not None else \
            self.table().schema  # replay-constructed results fall back
        return DistributedDataset(
            [BlockMeta(num_rows=self.num_rows, ref=self.ref)], schema)


@dataclass(frozen=True)
class _WindowSpec:
    size: int
    slide: int
    keys: Tuple[str, ...]
    aggs: Tuple[Tuple[str, str], ...]   # (column, op) pairs, output order

    def primitives(self) -> List[Tuple[str, str]]:
        """The decomposable (op, column) partials the spec needs (mean
        expands to sum+count), deduplicated, stable order."""
        need: List[Tuple[str, str]] = []
        for c, op in self.aggs:
            ops = ("sum", "count") if op == "mean" else (op,)
            for p in ops:
                if (p, c) not in need:
                    need.append((p, c))
        return need


def read_stream(source: StreamSource, session=None,
                name: Optional[str] = None) -> "ContinuousPipeline":
    """Open a continuous pipeline over ``source`` on an ETL session
    (default: the active one)."""
    if session is None:
        from raydp_tpu.context import active_session
        session = active_session()
    if session is None:
        raise ValueError("read_stream needs a live session: pass session= "
                         "or call raydp_tpu.init() first")
    return ContinuousPipeline(source, session, name=name)


class ContinuousPipeline:
    """See module docstring. Build with :func:`read_stream`, shape with
    :meth:`transform` / :meth:`window`, then either drive it inline
    (:meth:`step` / :meth:`epochs`) or in the background (:meth:`start`)
    while consumers follow :meth:`epoch_stream`."""

    def __init__(self, source: StreamSource, session, name: Optional[str] = None):
        self.source = source
        self.session = session
        self.name = name or f"stream-{uuid.uuid4().hex[:6]}"
        self._transform: Optional[Callable] = None
        self._window: Optional[_WindowSpec] = None
        self._lock = threading.Lock()
        #: epoch -> (partial refs, partial schema bytes)
        self._partials: Dict[int, Tuple[List[ObjectRef], bytes]] = {}  # guarded-by: _lock
        #: epoch -> (generation, result ref) of the published epoch blob
        self._results: Dict[int, Tuple[int, ObjectRef]] = {}  # guarded-by: _lock
        self._stage_key = f"stream:{self.name}"
        self._begun = False
        self._closed = False
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        self._sink_error: Optional[BaseException] = None
        # counters for report()
        self._epoch_walls: List[float] = []
        self._rows_in = 0
        self._windows_closed = 0
        self._replays = 0

    # ---- builder surface ----------------------------------------------------
    def transform(self, fn: Callable) -> "ContinuousPipeline":
        """Per-epoch plan builder: ``fn(df) -> df`` over the micro-batch
        frame, with the whole DataFrame API available (filter/project/
        groupagg/joins against static frames of the same session). Must be
        deterministic — it is re-run verbatim on replay."""
        self._transform = fn
        return self

    def window(self, size: int, keys: List[str], aggs: Dict[str, Any],
               slide: Optional[int] = None) -> "ContinuousPipeline":
        """Windowed aggregation over epoch ids: every ``slide`` epochs
        (default ``size`` — tumbling), the window of the last ``size``
        epochs merges its per-epoch partials. ``aggs`` maps column ->
        op (or list of ops) from sum/count/min/max/mean; output columns
        are named ``<column>_<op>``."""
        if size < 1 or (slide is not None and slide < 1):
            raise ValueError("window size/slide must be >= 1")
        pairs: List[Tuple[str, str]] = []
        for c, ops in aggs.items():
            for op in ([ops] if isinstance(ops, str) else list(ops)):
                if op not in _WINDOW_OPS:
                    raise ValueError(f"unsupported window op {op!r}; "
                                     f"have {_WINDOW_OPS}")
                pairs.append((c, op))
        self._window = _WindowSpec(size=int(size), slide=int(slide or size),
                                   keys=tuple(keys), aggs=tuple(pairs))
        return self

    # ---- the epoch step ------------------------------------------------------
    def step(self, timeout_s: Optional[float] = None) -> Optional[EpochResult]:
        """Run ONE epoch inline: poll the source, run the transform as an
        engine action, seal + publish the result, materialize window
        partials, close any due windows. None when the source had nothing
        within the poll timeout."""
        if self._closed:
            raise StreamError(f"pipeline {self.name} is closed")
        mb = self.source.next_batch(timeout_s)
        if mb is None:
            return None
        t0 = time.perf_counter()
        with profiler.trace("stream:epoch", "stream", pipeline=self.name,
                            epoch=mb.epoch, rows=mb.table.num_rows):
            key = f"{self.name}|{mb.epoch}"
            rule = faults.check("stream.epoch", key=key)
            drop_after = rule is not None and rule.action == "drop"
            if rule is not None and not drop_after:
                faults.apply(rule, "stream.epoch")
            result_ref, nrows, schema = self._run_epoch(mb.epoch, mb.table)
            self._publish(mb.epoch, 1, result_ref)
            if drop_after:
                # the chaos plane's epoch-blob loss: the freshly sealed
                # partials (or, windowless, the result blob) vanish
                # post-commit — the merge/consumer path must replay
                self._drop_epoch_blobs(mb.epoch)
            windows = [self._close_window(s, mb.epoch)
                       for s in self._due_windows(mb.epoch)]
        wall = time.perf_counter() - t0
        self._rows_in += mb.table.num_rows
        self._epoch_walls.append(wall)
        if len(self._epoch_walls) > 4096:
            del self._epoch_walls[:-4096]
        metrics.inc("stream_epochs_total")
        metrics.inc("stream_rows_total", mb.table.num_rows)
        metrics.observe("stream_epoch_seconds", wall)
        self._retire_old(mb.epoch)
        return EpochResult(epoch=mb.epoch, input_rows=mb.table.num_rows,
                           ref=result_ref, num_rows=nrows, wall_s=wall,
                           schema=schema, windows=windows)

    def _run_epoch(self, epoch: int, table: pa.Table,
                   replay: bool = False
                   ) -> Tuple[ObjectRef, int, pa.Schema]:
        """The epoch's engine work: frame the batch, run the transform
        action, seal ONE result blob, materialize the window partial.
        Deterministic — the replay path runs exactly this."""
        parts = int(knobs.get("RDT_STREAM_MAX_PARTITIONS")) \
            or max(1, min(len(self.session.executors),
                          table.num_rows or 1))
        in_df = self.session.createDataFrame(table, num_partitions=parts)
        in_refs = list(in_df._plan.refs)
        try:
            df = self._transform(in_df) if self._transform else in_df
            out = self.session.engine.collect(df._plan)
            # one sealed blob per epoch: the unit the ledger publishes and
            # consumers ranged-fetch (combine_chunks so a replayed seal is
            # byte-identical regardless of upstream chunking)
            result_ref = get_client().put_arrow(
                out.combine_chunks(), owner=self.session.master_name)
            if self._window is not None:
                prefs, pschema, _ = self.session.engine.materialize(
                    self._partial_frame(df)._plan,
                    owner=self.session.master_name)
                with self._lock:
                    old = self._partials.get(epoch)
                    self._partials[epoch] = (prefs, pschema)
                if replay and old is not None:
                    self._free_refs(old[0])  # superseded (lost) partials
        finally:
            self._free_refs(in_refs)
        return result_ref, out.num_rows, out.schema

    def _partial_frame(self, df):
        from raydp_tpu.etl import functions as F
        assert self._window is not None
        aggs = [getattr(F, op)(c).alias(f"__{op}_{c}")
                for op, c in self._window.primitives()]
        return df.groupBy(*self._window.keys).agg(*aggs)

    def _ensure_begun(self) -> None:
        """Open the ledger stage exactly once — from the first publish OR
        from a consumer attaching before any epoch ran (else its first
        poll would race the stage into an unknown-stage abort)."""
        with self._lock:
            if self._begun:
                return
            get_client().stream_begin(self._stage_key, 0)  # unbounded
            self._begun = True

    def _publish(self, epoch: int, gen: int, ref: ObjectRef) -> None:
        client = get_client()
        self._ensure_begun()
        old = None
        with self._lock:
            prev = self._results.get(epoch)
            if prev is not None:
                gen = max(gen, prev[0] + 1)
                old = prev[1]
            self._results[epoch] = (gen, ref)
        client.stream_publish(self._stage_key, epoch, gen, ref.id,
                              ref.size, [(0, ref.size)])
        if gen > 1:
            metrics.record_event("stream_reseal", stage=self._stage_key,
                                 map_id=epoch, gen=gen)
            if old is not None:
                self._free_refs([old])

    # ---- windows -------------------------------------------------------------
    def _due_windows(self, epoch: int) -> List[int]:
        """Start epochs of windows that close exactly at ``epoch``."""
        w = self._window
        if w is None:
            return []
        s = epoch - w.size + 1
        return [s] if s >= 0 and s % w.slide == 0 else []

    def _close_window(self, start: int, end: int) -> WindowResult:
        """Merge the window's per-epoch partials — with exactly-once
        replay: a lost partial blob re-derives its epoch from the source
        journal and the merge retries, up to RDT_STREAM_REPLAY_ROUNDS."""
        from raydp_tpu.etl.engine import StageError as EngineStageError
        rounds = max(0, int(knobs.get("RDT_STREAM_REPLAY_ROUNDS")))
        with profiler.trace("stream:window", "stream", pipeline=self.name,
                            start=start, end=end):
            for attempt in range(rounds + 1):
                try:
                    table = self._merge_window(start, end)
                    break
                except (EngineStageError, ObjectLostError) as err:
                    lost = self._lost_epochs(start, end)
                    if not lost or attempt >= rounds:
                        raise StreamError(
                            f"window [{start}, {end}] merge failed after "
                            f"{attempt} replay rounds (lost epochs: "
                            f"{lost})") from err
                    for ep in lost:
                        self._replay_epoch(ep, reason="window merge")
        self._windows_closed += 1
        metrics.inc("stream_windows_total")
        return WindowResult(start=start, end=end, table=table)

    def _merge_window(self, start: int, end: int) -> pa.Table:
        from raydp_tpu.etl import functions as F
        from raydp_tpu.etl import plan as P
        from raydp_tpu.etl.expressions import col
        from raydp_tpu.etl.frame import DataFrame
        w = self._window
        assert w is not None
        with self._lock:
            missing = [e for e in range(start, end + 1)
                       if e not in self._partials]
            refs = [r for e in range(start, end + 1)
                    for r in self._partials.get(e, ([], b""))[0]]
            schema = self._partials.get(end, (None, None))[1]
        if missing:
            raise StreamError(f"window [{start}, {end}] is missing epochs "
                              f"{missing} (retention too short?)")
        union = DataFrame(self.session, P.InMemory(list(refs), schema))
        merge = {"sum": F.sum, "count": F.sum, "min": F.min, "max": F.max}
        aggs = [merge[op](f"__{op}_{c}").alias(f"__{op}_{c}")
                for op, c in w.primitives()]
        out = union.groupBy(*w.keys).agg(*aggs)
        names = []
        for c, op in w.aggs:
            name = f"{c}_{op}"
            if op == "mean":
                # float division explicitly: int sum / int count would
                # truncate under arrow's integer divide
                out = out.withColumn(
                    name, col(f"__sum_{c}").cast("float64")
                    / col(f"__count_{c}").cast("float64"))
            else:
                out = out.withColumn(name, col(f"__{op}_{c}"))
            names.append(name)
        out = out.select(*(list(w.keys) + names))
        table = self.session.engine.collect(out._plan)
        return table.sort_by([(k, "ascending") for k in w.keys])

    # ---- exactly-once replay -------------------------------------------------
    def _lost_epochs(self, start: int, end: int) -> List[int]:
        """Window epochs with any partial blob missing from the store
        (fresh lookups — the memo may hold stale entries for lost blobs)."""
        with self._lock:
            span = {e: list(self._partials.get(e, ([], b""))[0])
                    for e in range(start, end + 1)}
        ids = [r.id for refs in span.values() for r in refs]
        found = get_client().lookup_many(ids, fresh=True)
        return [e for e, refs in span.items()
                if any(r.id not in found for r in refs)]

    def _replay_epoch(self, epoch: int, reason: str) -> None:
        """Re-derive one epoch from the source journal: same rows, same
        transform, same partial action — byte-identical by the source's
        replay contract. The result blob re-publishes under gen+1 so any
        in-flight consumer discards and refetches."""
        logger.warning("pipeline %s replaying lost epoch %d (%s)",
                       self.name, epoch, reason)
        table = self.source.replay(epoch)
        ref, _, _ = self._run_epoch(epoch, table, replay=True)
        self._publish(epoch, 2, ref)   # _publish bumps to max(prev+1, 2)
        self._replays += 1
        metrics.inc("stream_replays_total")
        metrics.record_event("stream_replay", pipeline=self.name,
                             epoch=epoch, reason=reason)

    def _drop_epoch_blobs(self, epoch: int) -> None:
        """The ``stream.epoch:drop`` chaos action: silently lose the
        epoch's just-sealed blobs (partials when windowed, else the
        published result) — the store-host-died model for streams."""
        with self._lock:
            victims = list(self._partials.get(epoch, ([], b""))[0]) \
                if self._window is not None \
                else [self._results[epoch][1]]
        logger.warning("stream.epoch:drop injected: freeing %d blob(s) of "
                       "epoch %d", len(victims), epoch)
        self._free_refs(victims)

    # ---- ledger consumers ----------------------------------------------------
    def epoch_stream(self, from_epoch: int = 0) -> "EpochStream":
        """A decoupled consumer over the pipeline's ledger stage: yields
        ``(epoch, table)`` in epoch order as seals land, replaying lost
        result blobs through the pipeline (gen+1 re-seals)."""
        self._ensure_begun()
        return EpochStream(self, from_epoch)

    # ---- driving -------------------------------------------------------------
    def epochs(self, max_epochs: Optional[int] = None,
               timeout_s: Optional[float] = None) -> Iterator[EpochResult]:
        """Drive the pipeline inline; stops after ``max_epochs``, when the
        source is exhausted, or when :meth:`stop` is called."""
        done = 0
        while not self._stopping and not self._closed:
            if max_epochs is not None and done >= max_epochs:
                return
            er = self.step(timeout_s)
            if er is None:
                if self.source.exhausted:
                    return
                continue
            done += 1
            yield er

    def start(self, sink: Optional[Callable[[EpochResult], None]] = None,
              max_epochs: Optional[int] = None) -> "ContinuousPipeline":
        """Run the epoch loop on a background thread; ``sink`` (if any) is
        called with every EpochResult. Consumers follow
        :meth:`epoch_stream`."""
        if self._thread is not None:
            raise StreamError("pipeline already started")

        def _loop():
            try:
                for er in self.epochs(max_epochs=max_epochs):
                    if sink is not None:
                        sink(er)
            except BaseException as e:  # noqa: BLE001 - surfaced via join/close
                self._sink_error = e
                logger.exception("pipeline %s loop failed", self.name)
                try:
                    get_client().stream_abort(self._stage_key, repr(e))
                except Exception:  # noqa: BLE001 - store may be gone too
                    pass

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name=f"rdt-stream-{self.name}")
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 60.0) -> None:
        """Stop the background loop after its current epoch."""
        self._stopping = True
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
        if self._sink_error is not None:
            err, self._sink_error = self._sink_error, None
            raise StreamError(
                f"pipeline {self.name} loop failed") from err

    # ---- retention / teardown ------------------------------------------------
    def _retire_old(self, epoch: int) -> None:
        """Free what the stream no longer needs: published result blobs
        older than the retention window, and window partials once no
        future window's span can reach them."""
        retain = max(1, int(knobs.get("RDT_STREAM_RETAIN")))
        victims: List[ObjectRef] = []
        with self._lock:
            for e in [e for e in self._results if e <= epoch - retain]:
                victims.append(self._results.pop(e)[1])
            if self._window is not None:
                w = self._window
                # the earliest epoch a not-yet-closed window can contain is
                # the smallest window start strictly after the start of the
                # window that closes at THIS epoch (before any window has
                # closed, that is start 0 — nothing retires)
                t = epoch - w.size + 1
                next_start = 0 if t < 0 else (t // w.slide + 1) * w.slide
                for e in [e for e in self._partials if e < next_start]:
                    victims.extend(self._partials.pop(e)[0])
        self._free_refs(victims)

    @staticmethod
    def _free_refs(refs: List[ObjectRef]) -> None:
        if not refs:
            return
        try:
            get_client().free(list(refs))
        except Exception:  # noqa: BLE001 - teardown/loss races are benign
            logger.debug("stream free failed", exc_info=True)

    def close(self) -> None:
        """Stop, close the ledger stage, and free every retained blob —
        the pipeline leaves zero orphaned store objects. A background
        loop's failure re-raises AFTER cleanup (the zero-orphan contract
        holds even for a failed pipeline)."""
        if self._closed:
            return
        loop_error: Optional[BaseException] = None
        try:
            self.stop()
        except StreamError as e:
            loop_error = e
        self._closed = True
        victims: List[ObjectRef] = []
        with self._lock:
            victims.extend(ref for _, ref in self._results.values())
            self._results.clear()
            for refs, _ in self._partials.values():
                victims.extend(refs)
            self._partials.clear()
        self._free_refs(victims)
        if self._begun:
            try:
                get_client().stream_close([self._stage_key])
            except Exception:  # noqa: BLE001 - store may already be down
                pass
        self.source.close()
        if loop_error is not None:
            raise loop_error

    def __enter__(self) -> "ContinuousPipeline":
        return self

    def __exit__(self, *exc) -> None:
        if exc and exc[0] is not None:
            # the body already failed: clean up without masking its error
            try:
                self.close()
            except StreamError:
                logger.warning("pipeline %s loop had also failed; body "
                               "error wins", self.name)
        else:
            self.close()

    # ---- reporting -----------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        walls = sorted(self._epoch_walls)

        def q(f):
            return round(walls[min(len(walls) - 1, int(f * len(walls)))], 4) \
                if walls else 0.0

        return {
            "pipeline": self.name,
            "epochs": self.source.epochs_emitted,
            "rows_in": self._rows_in,
            "windows_closed": self._windows_closed,
            "replays": self._replays,
            "epoch_p50_s": q(0.50),
            "epoch_p99_s": q(0.99),
            "epoch_max_s": round(walls[-1], 4) if walls else 0.0,
        }


class EpochStream:
    """Ledger-following consumer: long-polls the pipeline's stage for new
    seals (exactly like a pipelined shuffle's reducers) and yields
    ``(epoch, table)`` in epoch order. A fetch that hits a lost blob asks
    the pipeline to replay the epoch (gen+1 re-seal) and refetches."""

    def __init__(self, pipeline: ContinuousPipeline, from_epoch: int = 0):
        self._pipe = pipeline
        self._next = from_epoch
        self._have: Dict[int, int] = {}      # map_id -> newest gen seen
        self._sealed: Dict[int, Tuple[int, str, int]] = {}  # epoch -> seal

    def next(self, timeout_s: float = 30.0) -> Optional[Tuple[int, pa.Table]]:
        """The next epoch's result table, or None when nothing sealed
        within the timeout. Raises StreamError once the stage closes."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        client = get_client()
        while True:
            if self._next in self._sealed:
                epoch = self._next
                gen, ref_id, size = self._sealed[epoch]
                ref = ObjectRef(id=ref_id, size=size, kind=KIND_ARROW)
                try:
                    table = client.get(ref)
                except ObjectLostError:
                    # lost between seal and fetch: replay → gen+1 re-seal,
                    # then poll again for the fresh ref
                    self._pipe._replay_epoch(epoch, reason="consumer fetch")
                    del self._sealed[epoch]
                    continue
                del self._sealed[epoch]
                self._next += 1
                return epoch, table
            wait = deadline - time.monotonic()
            if wait <= 0:
                return None
            resp = client.stream_poll(self._pipe._stage_key, 0,
                                      have=dict(self._have),
                                      timeout_s=min(wait, 10.0))
            for map_id, gen, ref_id, size, _off, _bsize in resp["events"]:
                self._have[map_id] = gen
                if map_id >= self._next:
                    self._sealed[map_id] = (gen, ref_id, size)
            if resp.get("aborted"):
                if self._next in self._sealed:
                    continue  # drain what is already sealed
                raise StreamError(
                    f"epoch stream over {self._pipe._stage_key} ended: "
                    f"{resp['aborted']}")

    @property
    def exhausted(self) -> bool:
        """True once the pipeline's source is done and every emitted epoch
        has been yielded — this consumer will never produce again."""
        return (self._pipe.source.exhausted
                and self._next >= self._pipe.source.epochs_emitted
                and not self._sealed)

    def __iter__(self) -> Iterator[Tuple[int, pa.Table]]:
        while True:
            try:
                item = self.next()
            except StreamError:
                return
            if item is None:
                if self.exhausted:
                    return
                continue
            yield item
