"""Streaming sources: Arrow micro-batches with monotonic epoch ids.

A :class:`StreamSource` turns an external feed into a sequence of **epochs**
— each ``next_batch()`` call yields one :class:`MicroBatch` carrying a
``pyarrow.Table`` and a monotonically increasing epoch id assigned by the
source. Three concrete sources cover the blueprint's ingestion shapes:

- :class:`FileTailSource` — directory watch / file tail: new parquet or csv
  files appearing under a path become micro-batches (optionally chunked to
  a row cap), the Kafka-less analogue of a landing-zone feed;
- :class:`ReplayLogSource` — a pre-recorded log of tables replayed in
  order, for backfills and deterministic tests;
- :class:`SyntheticSource` — rows derived from ``make_batch(epoch)``, for
  load generation and benches (optionally rate-limited).

**Replay contract (exactly-once).** Every source can re-derive an emitted
epoch: ``replay(epoch)`` returns a table byte-identical to the one
``next_batch`` originally produced for that epoch. This is the streaming
twin of the batch engine's lineage recipes — when a downstream epoch blob
is lost (``ObjectLostError``), the pipeline replays the epoch through the
same deterministic path instead of double-reading the feed. FileTail keeps
``(path, offset, rows)`` specs and re-reads the file; ReplayLog indexes its
log; Synthetic re-invokes its generator. The journal is bounded by
``RDT_STREAM_RETAIN`` epochs — a replay older than the retention window
fails loudly rather than silently re-ingesting different rows.
"""

from __future__ import annotations

import glob
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import pyarrow as pa

from raydp_tpu import knobs
from raydp_tpu.log import get_logger

logger = get_logger("stream.sources")


class StreamError(RuntimeError):
    """A continuous pipeline failed in a way replay cannot absorb (source
    exhausted its journal, replay rounds exhausted, pipeline closed)."""


@dataclass(frozen=True)
class MicroBatch:
    """One epoch's rows. Epoch ids are assigned by the source,
    monotonically from 0, with no gaps."""

    epoch: int
    table: pa.Table


class StreamSource:
    """Base: assigns epoch ids and keeps the bounded replay journal.

    Subclasses implement ``_next(timeout_s)`` (the rows of the next epoch,
    or None when nothing is ready yet) and ``_rederive(spec)`` (rebuild an
    epoch's table from the journal entry ``_journal_spec`` stored for it).
    The default journal entry is the table itself (ReplayLog/small feeds);
    sources with a cheaper recipe (FileTail's file ranges, Synthetic's
    generator args) override ``_journal_spec`` to avoid pinning every
    emitted table in driver memory."""

    def __init__(self):
        self._epoch = 0
        self._lock = threading.Lock()
        self._journal: Dict[int, object] = {}  # guarded-by: _lock

    # -- subclass surface -----------------------------------------------------
    def _next(self, timeout_s: float) -> Optional[pa.Table]:
        raise NotImplementedError

    def _journal_spec(self, epoch: int, table: pa.Table) -> object:
        return table

    def _rederive(self, spec: object) -> pa.Table:
        assert isinstance(spec, pa.Table)
        return spec

    # -- pipeline surface -----------------------------------------------------
    def next_batch(self, timeout_s: Optional[float] = None
                   ) -> Optional[MicroBatch]:
        """The next epoch's rows, or None if the feed has nothing yet
        (poll again) — an exhausted finite source also returns None forever
        (``exhausted`` distinguishes the two)."""
        if timeout_s is None:
            timeout_s = float(knobs.get("RDT_STREAM_POLL_TIMEOUT_S"))
        table = self._next(timeout_s)
        if table is None:
            return None
        retain = max(1, int(knobs.get("RDT_STREAM_RETAIN")))
        with self._lock:
            epoch = self._epoch
            self._epoch += 1
            self._journal[epoch] = self._journal_spec(epoch, table)
            for e in [e for e in self._journal if e <= epoch - retain]:
                del self._journal[e]
        return MicroBatch(epoch, table)

    def replay(self, epoch: int) -> pa.Table:
        """Byte-identical re-derivation of an already-emitted epoch."""
        with self._lock:
            spec = self._journal.get(epoch)
        if spec is None:
            raise StreamError(
                f"epoch {epoch} is outside the replay journal "
                f"(RDT_STREAM_RETAIN={knobs.get('RDT_STREAM_RETAIN')}, "
                f"newest={self._epoch - 1})")
        return self._rederive(spec)

    @property
    def exhausted(self) -> bool:
        """True once a finite source will never emit again (infinite
        sources always return False)."""
        return False

    @property
    def epochs_emitted(self) -> int:
        return self._epoch

    def close(self) -> None:
        with self._lock:
            self._journal.clear()


# ---- file tail / directory watch --------------------------------------------

def _read_rows(path: str, offset: int, rows: int) -> pa.Table:
    """``rows`` rows of ``path`` starting at row ``offset`` (the FileTail
    journal recipe; also its forward read)."""
    if path.endswith((".parquet", ".pq")):
        import pyarrow.parquet as pq
        table = pq.read_table(path)
    else:
        import pyarrow.csv as pacsv
        table = pacsv.read_csv(path)
    return table.slice(offset, rows)


class FileTailSource(StreamSource):
    """Watch a directory (or glob) for new parquet/csv files; each new file
    becomes one micro-batch, chunked to ``rows_per_batch`` when set. Files
    are consumed in sorted-name order (the landing-zone convention:
    writers name files monotonically); a file must be fully written before
    it appears under the watched name (write-then-rename)."""

    def __init__(self, path: str, pattern: str = "*.parquet",
                 rows_per_batch: Optional[int] = None):
        super().__init__()
        self._path = path
        self._pattern = pattern
        self._rows_per_batch = rows_per_batch
        self._seen: set = set()
        #: (path, row offset) of the partially consumed head file
        self._cursor: Optional[Tuple[str, int]] = None

    def _candidates(self) -> List[str]:
        if os.path.isdir(self._path):
            return sorted(glob.glob(os.path.join(self._path, self._pattern)))
        return sorted(glob.glob(self._path))

    def _next(self, timeout_s: float) -> Optional[pa.Table]:
        deadline = time.monotonic() + max(0.0, timeout_s)
        while True:
            if self._cursor is not None:
                path, off = self._cursor
                cap = self._rows_per_batch
                table = _read_rows(path, off, cap if cap else (1 << 62))
                if table.num_rows == 0:
                    self._cursor = None  # fully consumed: fall through
                else:
                    # a full chunk may have more rows behind it; a short
                    # one exhausted the file
                    self._cursor = ((path, off + cap)
                                    if cap and table.num_rows == cap
                                    else None)
                    self._last_spec = (path, off, table.num_rows)
                    return table
            fresh = [p for p in self._candidates() if p not in self._seen]
            if fresh:
                self._seen.add(fresh[0])
                self._cursor = (fresh[0], 0)
                continue
            if time.monotonic() >= deadline:
                return None
            time.sleep(min(0.05, max(0.001, timeout_s)))

    def _journal_spec(self, epoch: int, table: pa.Table) -> object:
        return self._last_spec  # (path, offset, rows) set by _next

    def _rederive(self, spec: object) -> pa.Table:
        path, off, rows = spec
        return _read_rows(path, off, rows)


# ---- replayed log ------------------------------------------------------------

class ReplayLogSource(StreamSource):
    """Replay a pre-recorded log of tables in order — one table per epoch
    (backfill / deterministic-test shape). The log IS the journal, so
    replay is an index and retention never drops it."""

    def __init__(self, log: Sequence[pa.Table], rate_hz: Optional[float] = None):
        super().__init__()
        self._log = list(log)
        self._rate_hz = rate_hz
        self._t_last = 0.0

    def _next(self, timeout_s: float) -> Optional[pa.Table]:
        i = self._epoch
        if i >= len(self._log):
            return None
        if self._rate_hz:
            wait = self._t_last + 1.0 / self._rate_hz - time.monotonic()
            if wait > 0:
                if wait > timeout_s:
                    time.sleep(timeout_s)
                    return None
                time.sleep(wait)
            self._t_last = time.monotonic()
        return self._log[i]

    def _journal_spec(self, epoch: int, table: pa.Table) -> object:
        return epoch  # the log itself re-derives any epoch

    def _rederive(self, spec: object) -> pa.Table:
        return self._log[int(spec)]

    def replay(self, epoch: int) -> pa.Table:
        if not 0 <= epoch < len(self._log):
            raise StreamError(f"epoch {epoch} outside the replayed log "
                              f"({len(self._log)} entries)")
        return self._log[epoch]

    @property
    def exhausted(self) -> bool:
        return self._epoch >= len(self._log)


# ---- synthetic rate source ---------------------------------------------------

class SyntheticSource(StreamSource):
    """Micro-batches derived from ``make_batch(epoch) -> pa.Table`` — the
    generator must be deterministic per epoch (that determinism IS the
    replay contract). ``rate_hz`` throttles emission; ``max_epochs`` makes
    the source finite."""

    def __init__(self, make_batch: Callable[[int], pa.Table],
                 rate_hz: Optional[float] = None,
                 max_epochs: Optional[int] = None):
        super().__init__()
        self._make = make_batch
        self._rate_hz = rate_hz
        self._max = max_epochs
        self._t_last = 0.0

    def _next(self, timeout_s: float) -> Optional[pa.Table]:
        if self._max is not None and self._epoch >= self._max:
            return None
        if self._rate_hz:
            wait = self._t_last + 1.0 / self._rate_hz - time.monotonic()
            if wait > 0:
                if wait > timeout_s:
                    time.sleep(timeout_s)
                    return None
                time.sleep(wait)
            self._t_last = time.monotonic()
        return self._make(self._epoch)

    def _journal_spec(self, epoch: int, table: pa.Table) -> object:
        return epoch

    def _rederive(self, spec: object) -> pa.Table:
        return self._make(int(spec))

    @property
    def exhausted(self) -> bool:
        return self._max is not None and self._epoch >= self._max
