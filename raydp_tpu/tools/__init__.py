"""Developer tooling that ships with the package (no runtime dependencies)."""
