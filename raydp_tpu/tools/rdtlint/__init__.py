"""rdtlint — project-native static analysis for raydp_tpu.

Four rule families, each encoding an invariant this repo's reviews kept
re-finding by hand (see doc/dev_lint.md for the full reference and the
annotation conventions):

- ``dispatcher-blocking`` — blocking primitives must not be reachable from
  RPC dispatcher entry points ("waits never park head dispatchers").
- ``lock-discipline`` — ``# guarded-by: _lock`` attributes are accessed
  under their lock.
- ``knob-registry`` — every ``RDT_*`` knob is declared in
  ``raydp_tpu/knobs.py``, read through it (never cached at import time when
  per-action), and the doc tables are generated from it.
- ``fault-site-sync`` — fault-injection sites agree across code,
  ``faults.KNOWN_SITES``, ``doc/fault_tolerance.md``, and test specs.

Run it::

    python -m raydp_tpu.tools.rdtlint raydp_tpu/

Exit code 0 = no unsuppressed violations. Deliberate exceptions carry an
inline ``# rdtlint: allow[<rule>] <reason>`` (the reason is mandatory).
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from raydp_tpu.tools.rdtlint import (
    rule_dispatcher, rule_faults, rule_knobs, rule_locks)
from raydp_tpu.tools.rdtlint.core import (
    RULES, Project, Report, Violation, apply_suppressions)

_RULE_CHECKS = {
    "dispatcher-blocking": rule_dispatcher.check,
    "lock-discipline": rule_locks.check,
    "knob-registry": rule_knobs.check,
    "fault-site-sync": rule_faults.check,
}


def run(paths: Iterable[str], root: Optional[str] = None,
        rules: Optional[Iterable[str]] = None) -> Report:
    """Lint ``paths`` and return the :class:`Report` (violations carry their
    suppression state; callers gate on ``report.unsuppressed``)."""
    project = Project.load(list(paths), root=root)
    violations: List[Violation] = list(project.errors)
    for name in (rules if rules is not None else RULES):
        violations.extend(_RULE_CHECKS[name](project))
    # rule 4 scans tests/benchmarks lazily; load order guarantees their
    # suppressions are visible here
    apply_suppressions(project, violations)
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return Report(violations, files_linted=len(project.files))


__all__ = ["run", "Report", "Violation", "Project", "RULES"]
