"""rdtlint — project-native static analysis for raydp_tpu.

Eight rule families, each encoding an invariant this repo's reviews kept
re-finding by hand (see doc/dev_lint.md for the full reference and the
annotation conventions):

- ``dispatcher-blocking`` — blocking primitives must not be reachable from
  RPC dispatcher entry points ("waits never park head dispatchers").
- ``lock-discipline`` — ``# guarded-by: _lock`` attributes are accessed
  under their lock.
- ``knob-registry`` — every ``RDT_*`` knob is declared in
  ``raydp_tpu/knobs.py``, read through it (never cached at import time when
  per-action), and the doc tables are generated from it.
- ``fault-site-sync`` — fault-injection sites agree across code,
  ``faults.KNOWN_SITES``, ``doc/fault_tolerance.md``, and test specs.
- ``rpc-surface`` — every literal ``*.call("name", ...)`` resolves to a
  real remote method with compatible arity, no underscore targets, the
  head's store proxies are complete, and the generated RPC table is fresh.
- ``step-registry`` — every ref-carrying ``Step`` class (declared via
  ``# carries-refs:``) is registered with the lineage-recovery and stream
  planes; result-ref keys stay in sync with ``engine._result_refs``.
- ``exc-contract`` — every ``RemoteError.exc_type`` string comparison names
  a real exception class (repo, builtin, or allowlisted external).
- ``telemetry-registry`` — every literal ``profiler.trace(...)`` span name,
  ``metrics.*`` metric name (with the right kind), and flight-recorder
  event kind is declared in ``raydp_tpu/metrics.py``, and the generated
  tables in doc/observability.md are fresh.

Run it::

    python -m raydp_tpu.tools.rdtlint raydp_tpu/

Exit code 0 = no unsuppressed violations. Deliberate exceptions carry an
inline ``# rdtlint: allow[<rule>] <reason>`` (the reason is mandatory).
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from raydp_tpu.tools.rdtlint import (
    rule_dispatcher, rule_exc, rule_faults, rule_knobs, rule_locks,
    rule_rpc, rule_steps, rule_telemetry)
from raydp_tpu.tools.rdtlint.core import (
    RULES, Project, Report, Violation, apply_suppressions)

_RULE_CHECKS = {
    "dispatcher-blocking": rule_dispatcher.check,
    "lock-discipline": rule_locks.check,
    "knob-registry": rule_knobs.check,
    "fault-site-sync": rule_faults.check,
    "rpc-surface": rule_rpc.check,
    "step-registry": rule_steps.check,
    "exc-contract": rule_exc.check,
    "telemetry-registry": rule_telemetry.check,
}


def run(paths: Iterable[str], root: Optional[str] = None,
        rules: Optional[Iterable[str]] = None) -> Report:
    """Lint ``paths`` and return the :class:`Report` (violations carry their
    suppression state; callers gate on ``report.unsuppressed``)."""
    project = Project.load(list(paths), root=root)
    violations: List[Violation] = list(project.errors)
    for name in (rules if rules is not None else RULES):
        violations.extend(_RULE_CHECKS[name](project))
    # rule 4 scans tests/benchmarks lazily; load order guarantees their
    # suppressions are visible here
    apply_suppressions(project, violations)
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return Report(violations, files_linted=len(project.files))


__all__ = ["run", "Report", "Violation", "Project", "RULES"]
