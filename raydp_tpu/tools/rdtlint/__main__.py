"""CLI: ``python -m raydp_tpu.tools.rdtlint [paths...]``.

Pure AST pass — no runtime spin-up; safe to run anywhere the sources parse.
Exit codes: 0 = clean (suppressed-only), 1 = unsuppressed violations,
2 = usage/parse failure. ``--json`` emits a machine-readable report;
``--write-rpc-docs`` regenerates the RPC-surface table in doc/dev_lint.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from raydp_tpu.tools.rdtlint import RULES, run


def _default_paths() -> list:
    here = os.path.dirname(os.path.abspath(__file__))
    return [os.path.dirname(os.path.dirname(here))]  # the package dir


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raydp_tpu.tools.rdtlint",
        description="project-native static analysis (doc/dev_lint.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: the raydp_tpu "
                         "package next to this tool)")
    ap.add_argument("--rule", action="append", choices=RULES, default=None,
                    help="run only this rule (repeatable)")
    ap.add_argument("--root", default=None,
                    help="repo root for cross-checks (default: nearest "
                         "pyproject.toml above the first path)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed violations")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output: {files_linted, "
                         "violations: [{file, line, rule, message, "
                         "suppressed, reason}]}")
    ap.add_argument("--write-rpc-docs", action="store_true",
                    help="regenerate the RPC-surface table in "
                         "doc/dev_lint.md from the linted sources")
    args = ap.parse_args(argv)

    paths = args.paths or _default_paths()
    if args.write_rpc_docs:
        from raydp_tpu.tools.rdtlint import surfaces
        from raydp_tpu.tools.rdtlint.core import Project

        try:
            project = Project.load(paths, root=args.root)
            changed = surfaces.write_doc_table(project)
        except (FileNotFoundError, ValueError) as e:
            print(f"rdtlint: {e}", file=sys.stderr)
            return 2
        for rel in changed:
            print(f"rewrote {rel}")
        if not changed:
            print("rpc-surface table already fresh")
        return 0

    try:
        report = run(paths, root=args.root, rules=args.rule)
    except FileNotFoundError as e:
        print(f"rdtlint: {e}", file=sys.stderr)
        return 2
    if report.files_linted == 0:
        # an empty run is a misconfiguration, never a clean tree
        print(f"rdtlint: no Python files under {' '.join(paths)}",
              file=sys.stderr)
        return 2
    if args.json:
        shown = report.violations if args.show_suppressed \
            else report.unsuppressed
        print(json.dumps({
            "files_linted": report.files_linted,
            "violations": [
                {"file": v.path, "line": v.line, "rule": v.rule,
                 "message": v.message, "suppressed": v.suppressed,
                 "reason": v.reason}
                for v in shown],
            "suppressed": len(report.suppressed),
        }, indent=2))
    else:
        print(report.render(show_suppressed=args.show_suppressed))
    return 1 if report.unsuppressed else 0


if __name__ == "__main__":
    raise SystemExit(main())
