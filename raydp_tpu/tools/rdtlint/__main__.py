"""CLI: ``python -m raydp_tpu.tools.rdtlint [paths...]``.

Pure AST pass — no runtime spin-up; safe to run anywhere the sources parse.
Exit codes: 0 = clean (suppressed-only), 1 = unsuppressed violations,
2 = usage/parse failure.
"""

from __future__ import annotations

import argparse
import os
import sys

from raydp_tpu.tools.rdtlint import RULES, run


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raydp_tpu.tools.rdtlint",
        description="project-native static analysis (doc/dev_lint.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: the raydp_tpu "
                         "package next to this tool)")
    ap.add_argument("--rule", action="append", choices=RULES, default=None,
                    help="run only this rule (repeatable)")
    ap.add_argument("--root", default=None,
                    help="repo root for cross-checks (default: nearest "
                         "pyproject.toml above the first path)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed violations")
    args = ap.parse_args(argv)

    paths = args.paths
    if not paths:
        here = os.path.dirname(os.path.abspath(__file__))
        paths = [os.path.dirname(os.path.dirname(here))]  # the package dir
    try:
        report = run(paths, root=args.root, rules=args.rule)
    except FileNotFoundError as e:
        print(f"rdtlint: {e}", file=sys.stderr)
        return 2
    if report.files_linted == 0:
        # an empty run is a misconfiguration, never a clean tree
        print(f"rdtlint: no Python files under {' '.join(paths)}",
              file=sys.stderr)
        return 2
    print(report.render(show_suppressed=args.show_suppressed))
    return 1 if report.unsuppressed else 0


if __name__ == "__main__":
    raise SystemExit(main())
