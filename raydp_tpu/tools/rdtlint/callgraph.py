"""A lightweight, name-resolved call graph over the package's AST.

Deliberately conservative: an edge exists only for a DIRECT call the pass can
resolve by name — ``self.method(...)`` within a class, ``func(...)`` to a
module-level or imported function, ``mod.func(...)`` through an import alias,
and ``inner()`` to a nested def. A function merely *referenced* — passed to
``threading.Thread(target=...)``, ``pool.submit(...)``, or completing a
future behind a :class:`DeferredReply` — creates **no** edge: running code on
another thread is exactly how a handler legitimately escapes the dispatcher,
so "no direct call" and "escaped the dispatcher" coincide by construction.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from raydp_tpu.tools.rdtlint import config
from raydp_tpu.tools.rdtlint.core import Project, SourceFile

# call descriptors: ("local", name) | ("module", name) | ("self", attr)
# | ("import_func", fullname) | ("module_attr", module_fullname, attr)
# | ("self_attr", attr, meth) — self.<attr>.<meth>() through an instance
#   attribute whose class is known (constructed in __init__, or assigned
#   from an annotated __init__ parameter)
CallRef = Tuple


@dataclass
class Blocking:
    line: int
    kind: str
    detail: str


@dataclass
class FunctionInfo:
    qualname: str
    name: str
    module: str
    class_name: Optional[str]
    rel: str                      # file, repo-relative
    line: int
    calls: List[Tuple[CallRef, int]] = field(default_factory=list)
    blocking: List[Blocking] = field(default_factory=list)
    locals_defs: Dict[str, str] = field(default_factory=dict)


@dataclass
class CallGraph:
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    #: module -> bare function name -> qualname
    module_funcs: Dict[str, Dict[str, str]] = field(default_factory=dict)
    #: module -> class name -> method name -> qualname
    classes: Dict[str, Dict[str, Dict[str, str]]] = field(
        default_factory=dict)
    #: per-module import alias -> module fullname
    mod_imports: Dict[str, Dict[str, str]] = field(default_factory=dict)
    #: per-module imported-function alias -> fullname
    func_imports: Dict[str, Dict[str, str]] = field(default_factory=dict)
    #: completion callbacks (add_done_callback): either an already-resolved
    #: qualname or an unresolved ("self", module, class, attr) /
    #: ("module", module, name) descriptor resolved once the whole index
    #: exists (the callback method may be defined later in the class body)
    callback_entries: List[Tuple[Tuple, int]] = field(default_factory=list)
    #: class names detected as RPC dispatch targets
    detected_entry_classes: List[str] = field(default_factory=list)
    #: (module, class) -> instance attr -> class name of what it holds
    attr_types: Dict[Tuple[str, str], Dict[str, str]] = field(
        default_factory=dict)

    # -- resolution -----------------------------------------------------------
    def resolve(self, module: str, class_name: Optional[str],
                ref: CallRef) -> Optional[str]:
        kind = ref[0]
        if kind == "local":
            return ref[1]  # already a qualname
        if kind == "self" and class_name:
            return self.classes.get(module, {}).get(
                class_name, {}).get(ref[1])
        if kind == "module":
            q = self.module_funcs.get(module, {}).get(ref[1])
            if q:
                return q
            full = self.func_imports.get(module, {}).get(ref[1])
            if full and full in self.functions:
                return full
            return None
        if kind == "import_func":
            return ref[1] if ref[1] in self.functions else None
        if kind == "module_attr":
            return self.module_funcs.get(ref[1], {}).get(ref[2])
        if kind == "self_attr" and class_name:
            held = self.attr_types.get((module, class_name), {}).get(ref[1])
            if held:
                return self._class_method(module, held, ref[2])
        return None

    def _class_method(self, prefer_module: str, cls: str,
                      meth: str) -> Optional[str]:
        q = self.classes.get(prefer_module, {}).get(cls, {}).get(meth)
        if q:
            return q
        for mod in sorted(self.classes):
            q = self.classes[mod].get(cls, {}).get(meth)
            if q:
                return q
        return None

    def entry_functions(self) -> List[Tuple[str, str]]:
        """(qualname, why) for every analysis entry point: public methods of
        dispatch-target classes + registered completion callbacks."""
        entries: List[Tuple[str, str]] = []
        names = set(config.ENTRY_CLASS_NAMES) | set(
            self.detected_entry_classes)
        for module, classes in self.classes.items():
            for cls, methods in classes.items():
                if cls not in names:
                    continue
                for meth, qual in methods.items():
                    if meth.startswith("_"):
                        continue  # MethodDispatcher refuses these remotely
                    entries.append((qual, f"RPC dispatch method {cls}.{meth}"))
        for desc, line in self.callback_entries:
            if desc[0] == "resolved":
                qual: Optional[str] = desc[1]
            elif desc[0] == "self":
                qual = self.classes.get(desc[1], {}).get(
                    desc[2], {}).get(desc[3])
            else:  # ("module", module, name)
                qual = self.module_funcs.get(desc[1], {}).get(desc[2])
            if qual and qual in self.functions:
                entries.append(
                    (qual, f"completion callback registered at line {line} "
                           "(runs on the RPC read loop / completing thread)"))
        return entries


# ---- blocking-call heuristics ------------------------------------------------

def _recv_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_str_join(call: ast.Call, recv: ast.AST) -> bool:
    """True when a ``.join(...)`` is a string/path join, not a thread join."""
    if isinstance(recv, ast.Constant) and isinstance(recv.value, str):
        return True
    rname = _recv_name(recv) or ""
    if rname in ("path", "pathsep", "sep", "linesep"):
        return True
    if any(kw.arg == "timeout" for kw in call.keywords):
        return False
    if len(call.args) == 1 and not call.keywords:
        a = call.args[0]
        if isinstance(a, ast.Constant) and isinstance(a.value, (int, float)):
            return False  # t.join(5.0)
        return True  # sep.join(iterable)
    return False


def _is_store_get(recv: ast.AST) -> bool:
    rname = _recv_name(recv)
    if rname is None:
        if isinstance(recv, ast.Call):
            return _recv_name(recv.func) == "get_client"
        return False
    low = rname.lower().lstrip("_")
    return (low in config.STORE_GET_RECEIVERS
            or rname.endswith(config.STORE_GET_SUFFIXES))


def classify_blocking(call: ast.Call) -> Optional[Tuple[str, str]]:
    """(kind, detail) when this call is a blocking primitive, else None."""
    f = call.func
    if isinstance(f, ast.Name):
        if f.id == "sleep":
            return ("sleep", "sleep()")
        if f.id == "wait":
            return ("wait", "wait(...) on futures")
        return None
    if not isinstance(f, ast.Attribute):
        return None
    a = f.attr
    if a == "sleep":
        return ("sleep", "time.sleep")
    if a == "result":
        return ("result", "Future.result() — may wait on work needing this "
                          "dispatcher pool")
    if a == "call":
        return ("rpc-call", "synchronous RpcClient.call round trip")
    if a == "wait":
        return ("wait", "event/condition wait")
    if a == "join":
        if _is_str_join(call, f.value):
            return None
        return ("join", "thread join")
    if a == "get":
        if _is_store_get(f.value):
            return ("store-get", "blocking store/queue get")
        return None
    return None


# ---- the indexing pass -------------------------------------------------------

class _Indexer(ast.NodeVisitor):
    def __init__(self, graph: CallGraph, src: SourceFile, module: str):
        self.g = graph
        self.src = src
        self.module = module
        self.class_stack: List[str] = []
        self.fn_stack: List[FunctionInfo] = []
        self.g.module_funcs.setdefault(module, {})
        self.g.classes.setdefault(module, {})
        self.g.mod_imports.setdefault(module, {})
        self.g.func_imports.setdefault(module, {})

    # imports ---------------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self.g.mod_imports[self.module][
                alias.asname or alias.name.split(".")[0]] = alias.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level or not node.module:
            return  # relative imports: out of scope for this pass
        for alias in node.names:
            local = alias.asname or alias.name
            full = f"{node.module}.{alias.name}"
            # could be a submodule (from raydp_tpu.etl import tasks) or a
            # function (from x import run_task_body); record as both and let
            # resolution pick whichever exists
            self.g.mod_imports[self.module][local] = full
            self.g.func_imports[self.module][local] = full

    # definitions -----------------------------------------------------------
    def _qualname(self, name: str) -> str:
        if self.fn_stack:
            return f"{self.fn_stack[-1].qualname}.<locals>.{name}"
        if self.class_stack:
            return f"{self.module}.{'.'.join(self.class_stack)}.{name}"
        return f"{self.module}.{name}"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if not self.fn_stack:
            self.class_stack.append(node.name)
            self.g.classes[self.module].setdefault(node.name, {})
            self._collect_attr_types(node)
            self.generic_visit(node)
            self.class_stack.pop()
        # classes defined inside functions: skip their internals

    def _collect_attr_types(self, cls: ast.ClassDef) -> None:
        """What class each ``self.X`` holds, when __init__ makes it obvious:
        ``self.x = SomeClass(...)`` or ``self.x = param`` with ``param``
        annotated (``job: "SPMDJob"``)."""
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            return
        ann: Dict[str, str] = {}
        for arg in init.args.args + init.args.kwonlyargs:
            a = arg.annotation
            name = None
            if isinstance(a, ast.Name):
                name = a.id
            elif isinstance(a, ast.Constant) and isinstance(a.value, str):
                name = a.value.split("[")[0].split(".")[-1].strip('"\' ')
            if name:
                ann[arg.arg] = name
        types: Dict[str, str] = {}
        for node in ast.walk(init):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            attr = None
            t = node.targets[0]
            if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)\
                    and t.value.id == "self":
                attr = t.attr
            if attr is None:
                continue
            v = node.value
            if isinstance(v, ast.Call) and isinstance(v.func, ast.Name):
                types[attr] = v.func.id
            elif isinstance(v, ast.Name) and v.id in ann:
                types[attr] = ann[v.id]
        if types:
            self.g.attr_types[(self.module, cls.name)] = types

    def _visit_function(self, node, name: str) -> None:
        qual = self._qualname(name)
        info = FunctionInfo(
            qualname=qual, name=name, module=self.module,
            class_name=self.class_stack[-1] if self.class_stack else None,
            rel=self.src.rel, line=node.lineno)
        self.g.functions[qual] = info
        if self.fn_stack:
            self.fn_stack[-1].locals_defs[name] = qual
        elif self.class_stack:
            self.g.classes[self.module][self.class_stack[-1]][name] = qual
        else:
            self.g.module_funcs[self.module][name] = qual
        self.fn_stack.append(info)
        self.generic_visit(node)
        self.fn_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_function(node, node.name)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_function(node, node.name)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_function(node, f"<lambda:{node.lineno}>")

    # calls -----------------------------------------------------------------
    def _call_ref(self, call: ast.Call) -> Optional[CallRef]:
        f = call.func
        if isinstance(f, ast.Name):
            for fn in reversed(self.fn_stack):
                if f.id in fn.locals_defs:
                    return ("local", fn.locals_defs[f.id])
            return ("module", f.id)
        if isinstance(f, ast.Attribute):
            v = f.value
            if isinstance(v, ast.Name):
                if v.id == "self":
                    return ("self", f.attr)
                target = self.g.mod_imports[self.module].get(v.id)
                if target:
                    return ("module_attr", target, f.attr)
            elif isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                return ("self_attr", v.attr, f.attr)
        return None

    def visit_Call(self, node: ast.Call) -> None:
        if self.fn_stack:
            fn = self.fn_stack[-1]
            ref = self._call_ref(node)
            if ref is not None:
                fn.calls.append((ref, node.lineno))
            blk = classify_blocking(node)
            if blk is not None:
                fn.blocking.append(Blocking(node.lineno, blk[0], blk[1]))
        self._detect_entry_patterns(node)
        self.generic_visit(node)

    def _detect_entry_patterns(self, node: ast.Call) -> None:
        fname = _recv_name(node.func) if isinstance(
            node.func, ast.Attribute) else (
            node.func.id if isinstance(node.func, ast.Name) else None)
        # MethodDispatcher(Cls(...)) / RpcServer(Cls(...), ...)
        if fname in ("MethodDispatcher", "RpcServer") and node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Call) and isinstance(a0.func, ast.Name):
                inner = a0
                if inner.func.id == "MethodDispatcher" and inner.args \
                        and isinstance(inner.args[0], ast.Call) \
                        and isinstance(inner.args[0].func, ast.Name):
                    inner = inner.args[0]
                if inner.func.id != "MethodDispatcher":
                    # dispatch through an intermediate variable the AST pass
                    # cannot follow: config.ENTRY_CLASS_NAMES covers those
                    self.g.detected_entry_classes.append(inner.func.id)
        # fut.add_done_callback(X): X runs on whatever thread completes fut —
        # for RPC client futures that is the connection's READ LOOP
        if fname == "add_done_callback" and node.args:
            cb = node.args[0]
            desc: Optional[Tuple] = None
            if isinstance(cb, ast.Name):
                for fn in reversed(self.fn_stack):
                    if cb.id in fn.locals_defs:
                        desc = ("resolved", fn.locals_defs[cb.id])
                        break
                if desc is None:
                    desc = ("module", self.module, cb.id)
            elif isinstance(cb, ast.Attribute) \
                    and isinstance(cb.value, ast.Name) \
                    and cb.value.id == "self" and self.class_stack:
                desc = ("self", self.module, self.class_stack[-1], cb.attr)
            elif isinstance(cb, ast.Lambda):
                desc = ("resolved", self._qualname(f"<lambda:{cb.lineno}>"))
            if desc:
                self.g.callback_entries.append((desc, node.lineno))


def build(project: Project,
          files: Optional[Sequence[SourceFile]] = None) -> CallGraph:
    graph = CallGraph()
    for src in (files if files is not None else project.files):
        _Indexer(graph, src, src.module_name(project.root)).visit(src.tree)
    return graph
