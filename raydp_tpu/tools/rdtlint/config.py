"""Project-native configuration of the rdtlint rules.

rdtlint is not a generic linter: these names encode *this* repo's runtime
architecture. Keep them in sync with the modules they describe (the
``fault-site-sync`` and ``knob-registry`` rules are self-syncing; this file
covers what cannot be derived from the AST alone).
"""

#: classes whose PUBLIC methods run on a bounded RPC dispatcher thread pool
#: (``RpcServer(MethodDispatcher(...))`` targets, actor dispatch targets, and
#: the store server the head proxies into). The dispatcher-blocking rule also
#: auto-detects ``MethodDispatcher(Cls(...))`` / ``RpcServer(Cls(...))``
#: constructions; this list covers targets built through intermediate
#: variables the AST pass cannot follow.
ENTRY_CLASS_NAMES = frozenset({
    "HeadService",        # runtime/head.py — the head's RPC surface
    "NodeAgentService",   # runtime/node_agent.py
    "ObjectStoreServer",  # runtime/object_store.py — head dispatchers proxy
                          # store_* calls straight into it
    "ShuffleStreamLedger",  # runtime/object_store.py — ditto, stream_* calls
    "EtlExecutor",        # etl/executor.py — actor dispatch target
    "EtlMaster",          # etl/master.py — actor dispatch target
    "_DriverService",     # spmd/job.py
    "_WorkerService",     # spmd/worker.py
})

#: attribute names whose *call* is treated as a blocking primitive by the
#: dispatcher-blocking rule (receiver heuristics in callgraph.py narrow the
#: noisy ones: ``.join`` skips str/os.path joins, ``.get`` only fires on
#: store/queue-shaped receivers)
BLOCKING_ATTRS = frozenset({
    "sleep",   # time.sleep — parks the thread outright
    "result",  # concurrent.futures.Future.result — may wait on work that
               # needs THIS dispatcher pool to complete (the classic
               # self-deadlock)
    "call",    # RpcClient.call — a synchronous round trip; a head handler
               # calling back into a peer can deadlock on pool exhaustion
    "wait",    # Event/Condition wait, long-polls
    "join",    # Thread.join
})

#: receiver names (or suffixes) for which a ``.get(...)`` call is treated as
#: a blocking store/queue read rather than a dict lookup
STORE_GET_RECEIVERS = frozenset({"client", "store", "queue", "q"})
STORE_GET_SUFFIXES = ("_client", "_store", "_queue")
