"""Project-native configuration of the rdtlint rules.

rdtlint is not a generic linter: these names encode *this* repo's runtime
architecture. Keep them in sync with the modules they describe (the
``fault-site-sync`` and ``knob-registry`` rules are self-syncing; this file
covers what cannot be derived from the AST alone).
"""

#: classes whose PUBLIC methods run on a bounded RPC dispatcher thread pool
#: (``RpcServer(MethodDispatcher(...))`` targets, actor dispatch targets, and
#: the store server the head proxies into). The dispatcher-blocking rule also
#: auto-detects ``MethodDispatcher(Cls(...))`` / ``RpcServer(Cls(...))``
#: constructions; this list covers targets built through intermediate
#: variables the AST pass cannot follow.
ENTRY_CLASS_NAMES = frozenset({
    "HeadService",        # runtime/head.py — the head's RPC surface
    "NodeAgentService",   # runtime/node_agent.py
    "ObjectStoreServer",  # runtime/object_store.py — head dispatchers proxy
                          # store_* calls straight into it
    "ShuffleStreamLedger",  # runtime/object_store.py — ditto, stream_* calls
    "EtlExecutor",        # etl/executor.py — actor dispatch target
    "EtlMaster",          # etl/master.py — actor dispatch target
    "_DriverService",     # spmd/job.py
    "_WorkerService",     # spmd/worker.py
})

#: attribute names whose *call* is treated as a blocking primitive by the
#: dispatcher-blocking rule (receiver heuristics in callgraph.py narrow the
#: noisy ones: ``.join`` skips str/os.path joins, ``.get`` only fires on
#: store/queue-shaped receivers)
BLOCKING_ATTRS = frozenset({
    "sleep",   # time.sleep — parks the thread outright
    "result",  # concurrent.futures.Future.result — may wait on work that
               # needs THIS dispatcher pool to complete (the classic
               # self-deadlock)
    "call",    # RpcClient.call — a synchronous round trip; a head handler
               # calling back into a peer can deadlock on pool exhaustion
    "wait",    # Event/Condition wait, long-polls
    "join",    # Thread.join
})

#: receiver names (or suffixes) for which a ``.get(...)`` call is treated as
#: a blocking store/queue read rather than a dict lookup
STORE_GET_RECEIVERS = frozenset({"client", "store", "queue", "q"})
STORE_GET_SUFFIXES = ("_client", "_store", "_queue")

# ---- rule: rpc-surface ------------------------------------------------------

#: the RPC server surfaces, keyed by the short surface tag the receiver map
#: below points into. Every ``*.call("name", ...)`` site with a literal method
#: name resolves against one of these (or their union). ``_WorkerService`` /
#: ``_ActorServer`` dispatch through a ``__call__(method, ...)`` if-chain
#: rather than a MethodDispatcher — the surface builder extracts their
#: ``method == "literal"`` branches.
RPC_SURFACE_CLASSES = {
    "head": ("HeadService",),            # runtime/head.py
    "agent": ("NodeAgentService",),      # runtime/node_agent.py — also the
                                         # machine-local payload server that
                                         # ObjectStoreClient._peer dials
    "store": ("ObjectStoreServer",),     # runtime/object_store.py — reached
                                         # through the head's store_* proxies
    "driver": ("_DriverService",),       # spmd/job.py
    "worker": ("_WorkerService",),       # spmd/worker.py (if-chain handler)
    "actor": ("_ActorServer", "EtlExecutor", "EtlMaster"),
}

#: call-site receiver name → surface tag. The name is the receiver variable
#: (``head.call``), its attribute (``self._head.call``, ``ctx.head.call``),
#: or the function that PRODUCED it (``self._head_client().call(...)``,
#: ``self._peer(addr).call(...)``). ``"*"`` means "any surface" — used for
#: generic handles whose target class is dynamic (ActorHandle, the bootstrap
#: RpcClient). Receivers not in this map are checked against the union too:
#: inside this package a literal ``.call("name")`` is always an RPC.
RPC_RECEIVER_SURFACES = {
    "head": "head",
    "_head": "head",
    "_head_client": "head",
    "agent": "agent",
    "_agent": "agent",
    "_peer": "agent",
    "driver": "driver",
    "stub": "worker",
    "handle": "*",
    "client": "*",
    # the serving plane's replica handles (serve/session.py) are executor
    # actors: serve_* call sites resolve strictly against the actor surface
    "replica": "actor",
    "_replica": "actor",
}

#: actor-runtime intrinsics served by ``_ActorServer.__call__`` BEFORE the
#: MethodDispatcher underscore guard — the only legitimate underscore-leading
#: remote names.
RPC_INTRINSIC_METHODS = frozenset({
    "__rdt_ping__", "__rdt_shutdown__", "__rdt_spans__",
    "__rdt_metrics__", "__rdt_clock__",
})

#: head proxy naming: ``HeadService.store_<m>`` forwards to
#: ``ObjectStoreServer.<m>`` (the shape StoreTableProxy relies on)
RPC_STORE_PROXY_PREFIX = "store_"

#: the client class whose ``self._server.<m>(...)`` calls define which store
#: methods must stay proxy-reachable from a driver/actor process
RPC_STORE_CLIENT_CLASS = "ObjectStoreClient"
RPC_STORE_SERVER_CLASS = "ObjectStoreServer"
RPC_HEAD_SERVICE_CLASS = "HeadService"

# ---- rule: step-registry ----------------------------------------------------

#: the class whose instances read a shuffle stage through the seal-stream
#: ledger — it carries no ObjectRefs itself (ranges arrive at run time), but
#: every task holding one must be routed/resolved through the stream plane
STEP_STREAM_SOURCE_CLASS = "StreamingRangeSource"

#: handler functions in etl/tasks.py that every REF-carrying (and
#: nested-task-carrying) step class must be isinstance-handled in
STEP_REF_HANDLERS = ("_patch_step_refs", "task_input_ids")

#: handler functions in etl/tasks.py that every STREAM-carrying step class
#: (and nested-task carrier) must be handled in — by isinstance, or by a
#: ``getattr(step, "<attr>", ...)`` literal on each stream attribute
STEP_STREAM_HANDLERS = ("stream_sources_of", "resolve_stream_sources")

#: result-dict keys through which a task result may carry store refs; the
#: executor must write ref-valued results only under these keys and
#: ``engine._result_refs`` must harvest every one (a key missing there is an
#: orphan-blob leak on every failed stage)
STEP_RESULT_REF_KEYS = ("ref", "bucket_refs", "consolidated_ref")

#: engine.py functions that must each isinstance-handle ``_StreamBucket``
#: (the pipelined stage's bucket placeholder): locality weighting, reduce
#: source construction, and stream-key tagging
STEP_STREAM_BUCKET_FUNCS = ("_locality", "_bucket_source", "_bucket_task")

# ---- rule: exc-contract -----------------------------------------------------

#: non-builtin exception names that may legitimately cross the RPC boundary
#: as ``RemoteError.exc_type`` strings without a class definition in this
#: repo (the rule validates builtins via the ``builtins`` module and repo
#: classes from the AST; everything else must be listed here)
EXC_EXTERNAL_ALLOWLIST = frozenset({
    # pyarrow: raised by Arrow kernels inside executor task bodies
    "ArrowException", "ArrowInvalid", "ArrowNotImplementedError",
    "ArrowKeyError", "ArrowTypeError", "ArrowIndexError",
    "ArrowMemoryError", "ArrowCapacityError", "ArrowSerializationError",
})
