"""rdtlint plumbing: source loading, comments, suppressions, the project view.

Everything here is a pure AST/text pass — no raydp_tpu runtime import, no
actor spin-up — so the CLI and the tier-1 test stay fast and runnable in any
environment that can parse the sources.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

#: the rule families (doc/dev_lint.md)
RULES = (
    "dispatcher-blocking",
    "lock-discipline",
    "knob-registry",
    "fault-site-sync",
    "rpc-surface",
    "step-registry",
    "exc-contract",
    "telemetry-registry",
)

_SUPPRESS_RE = re.compile(
    r"#\s*rdtlint:\s*allow\[([a-z-]+)\]\s*(.*)$")
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*(\w+)")


@dataclass
class Violation:
    rule: str
    path: str          # repo-root-relative
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def render(self) -> str:
        tag = f" (suppressed: {self.reason})" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


class SourceFile:
    """One parsed source file: AST with parent links + per-line comments +
    suppression/annotation lookup."""

    def __init__(self, path: str, rel: str):
        self.path = path
        self.rel = rel
        with open(path, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=path)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._rdt_parent = node  # type: ignore[attr-defined]
        #: line -> full comment text (from tokenize, so strings never
        #: masquerade as comments)
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass

    # -- annotations ----------------------------------------------------------
    def comment_only_line(self, line: int) -> bool:
        """True when ``line`` holds nothing but a comment — a trailing
        comment on a statement must never annotate the NEXT line."""
        if not (1 <= line <= len(self.lines)):
            return False
        return self.lines[line - 1].lstrip().startswith("#")

    def suppression(self, rule: str, line: int) -> Optional[str]:
        """The reason of an ``# rdtlint: allow[rule] reason`` covering
        ``line`` (same line, or a comment-only line directly above), or
        None. An allow with an empty reason does NOT count — the reason is
        the audit trail."""
        for cand in (line, line - 1):
            c = self.comments.get(cand)
            if not c or (cand != line and not self.comment_only_line(cand)):
                continue
            m = _SUPPRESS_RE.search(c)
            if m and m.group(1) == rule and m.group(2).strip():
                return m.group(2).strip()
        return None

    def guarded_by(self, line: int, allow_above: bool = False
                   ) -> Optional[str]:
        """The guard name of a ``# guarded-by: _lock`` annotation on
        ``line`` (optionally also a comment-only line directly above)."""
        for cand in ((line, line - 1) if allow_above else (line,)):
            c = self.comments.get(cand)
            if not c or (cand != line and not self.comment_only_line(cand)):
                continue
            m = _GUARDED_BY_RE.search(c)
            if m:
                return m.group(1)
        return None

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_rdt_parent", None)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def module_name(self, root: str) -> str:
        rel = os.path.relpath(self.path, root)
        mod = rel[:-3] if rel.endswith(".py") else rel
        parts = mod.replace(os.sep, "/").split("/")
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)


def find_repo_root(start: str) -> str:
    """Walk up from ``start`` to the nearest directory with a pyproject.toml
    (fallback: the starting directory itself)."""
    cur = os.path.abspath(start if os.path.isdir(start)
                          else os.path.dirname(start) or ".")
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return os.path.abspath(start if os.path.isdir(start)
                                   else os.path.dirname(start) or ".")
        cur = nxt


def _iter_py(path: str) -> Iterable[str]:
    if os.path.isfile(path):
        if path.endswith(".py"):
            yield path
        return
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = [d for d in dirnames
                       if d != "__pycache__" and not d.startswith(".")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


@dataclass
class Project:
    """What one lint run sees: the target files plus repo-level context for
    the cross-checks (docs, test fault specs)."""

    root: str
    files: List[SourceFile] = field(default_factory=list)
    errors: List[Violation] = field(default_factory=list)
    _extra: Dict[str, List[SourceFile]] = field(default_factory=dict)

    @classmethod
    def load(cls, paths: Iterable[str],
             root: Optional[str] = None) -> "Project":
        paths = [os.path.abspath(p) for p in paths]
        for p in paths:
            if not os.path.exists(p):
                # fail LOUDLY: a typo'd CI path must not report a clean tree
                raise FileNotFoundError(f"no such file or directory: {p}")
        root = os.path.abspath(root) if root else find_repo_root(paths[0])
        proj = cls(root=root)
        seen = set()
        for p in paths:
            for f in _iter_py(p):
                f = os.path.abspath(f)
                if f in seen:
                    continue
                seen.add(f)
                rel = os.path.relpath(f, root)
                try:
                    proj.files.append(SourceFile(f, rel))
                except SyntaxError as e:
                    proj.errors.append(Violation(
                        rule="parse", path=rel, line=e.lineno or 1,
                        message=f"syntax error: {e.msg}"))
        return proj

    def extra_files(self, subdir: str) -> List[SourceFile]:
        """Parsed files of a repo subdir (``tests``, ``benchmarks``) for the
        cross-checks — cached, empty when the dir is absent."""
        if subdir not in self._extra:
            out: List[SourceFile] = []
            base = os.path.join(self.root, subdir)
            if os.path.isdir(base):
                for f in _iter_py(base):
                    try:
                        out.append(SourceFile(
                            f, os.path.relpath(f, self.root)))
                    except SyntaxError:
                        pass
            self._extra[subdir] = out
        return self._extra[subdir]

    def find_file(self, rel_suffix: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.rel.replace(os.sep, "/").endswith(rel_suffix):
                return f
        return None


def apply_suppressions(project: Project,
                       violations: List[Violation]) -> List[Violation]:
    """Mark violations covered by an ``allow[...]`` annotation as
    suppressed (the tool still counts and prints them)."""
    by_rel = {f.rel: f for f in project.files}
    for extra in project._extra.values():
        for f in extra:
            by_rel.setdefault(f.rel, f)
    for v in violations:
        f = by_rel.get(v.path)
        if f is None:
            continue
        reason = f.suppression(v.rule, v.line)
        if reason is not None:
            v.suppressed = True
            v.reason = reason
    return violations


def marker_block_violation(rule: str, rel: str, text: str, begin: str,
                           end: str, expected: str, what: str,
                           regen_cmd: str) -> Optional[Violation]:
    """The one drift check shared by every generated-doc fence (knob tables,
    the RPC-surface table): missing markers or a block differing from
    ``expected`` is a violation pointing at ``regen_cmd``."""
    if begin not in text or end not in text:
        return Violation(
            rule=rule, path=rel, line=1,
            message=f"missing generated {what} table markers ({begin})")
    block = begin + text.split(begin, 1)[1].split(end, 1)[0] + end
    if block != expected:
        line = text[:text.index(begin)].count("\n") + 1
        return Violation(
            rule=rule, path=rel, line=line,
            message=f"generated {what} table is stale — run `{regen_cmd}`")
    return None


@dataclass
class Report:
    violations: List[Violation]
    files_linted: int = 0

    @property
    def unsuppressed(self) -> List[Violation]:
        return [v for v in self.violations if not v.suppressed]

    @property
    def suppressed(self) -> List[Violation]:
        return [v for v in self.violations if v.suppressed]

    def render(self, show_suppressed: bool = False) -> str:
        lines = [v.render() for v in self.unsuppressed]
        if show_suppressed:
            lines += [v.render() for v in self.suppressed]
        lines.append(
            f"rdtlint: {len(self.unsuppressed)} violation(s), "
            f"{len(self.suppressed)} suppressed")
        return "\n".join(lines)
