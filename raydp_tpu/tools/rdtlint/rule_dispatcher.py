"""Rule ``dispatcher-blocking``: no blocking primitive may be reachable from
an RPC dispatcher entry point by direct calls.

The invariant this encodes (PAPER.md §(a) actor discipline, load-bearing
since the pipelined shuffle): **waits never park head dispatchers**. An RPC
handler runs on a bounded thread pool; if it blocks on work that needs that
same pool — a long-poll, a ``Future.result`` completed by another handler, a
synchronous call back over the connection that is delivering it — the pool
can wedge entirely. Both historical deadlocks had this shape:

- PR 3: ``_free_late_result`` fired as a Future done-callback on an executor
  connection's READ LOOP and synchronously called back over that same
  connection — blocking the only thread able to deliver its own response.
- PR 7: a streaming ``run_task`` waiting for seal notifications on a bounded
  dispatcher thread while the map tasks it waited on queued behind it.

Escapes are structural: hand the blocking work to a spawned thread and (for
handlers) return a ``DeferredReply`` — a function that is only *referenced*
(thread target, ``pool.submit``, done-callback) is not an edge, so escaped
work is invisible to the traversal by construction.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

from raydp_tpu.tools.rdtlint import callgraph
from raydp_tpu.tools.rdtlint.core import Project, Violation

RULE = "dispatcher-blocking"


def check(project: Project) -> List[Violation]:
    graph = callgraph.build(project)
    entries = graph.entry_functions()
    # BFS over direct-call edges from every entry, remembering one shortest
    # path per reached function for the report
    reached: Dict[str, Tuple[str, List[str]]] = {}  # qual -> (why, path)
    for entry_qual, why in entries:
        if entry_qual not in graph.functions:
            continue
        q = deque([(entry_qual, [entry_qual])])
        while q:
            qual, path = q.popleft()
            if qual in reached:
                continue
            reached[qual] = (why if qual == entry_qual
                             else reached[path[0]][0], path)
            fn = graph.functions[qual]
            for ref, _line in fn.calls:
                target = graph.resolve(fn.module, fn.class_name, ref)
                if target and target in graph.functions \
                        and target not in reached:
                    q.append((target, path + [target]))

    out: List[Violation] = []
    seen: set = set()
    for qual, (why, path) in sorted(reached.items()):
        fn = graph.functions[qual]
        for blk in fn.blocking:
            key = (fn.rel, blk.line)
            if key in seen:
                continue
            seen.add(key)
            chain = " -> ".join(p.rsplit(".", 1)[-1] for p in path)
            out.append(Violation(
                rule=RULE, path=fn.rel, line=blk.line,
                message=(
                    f"{blk.detail} runs on an RPC dispatcher/read-loop "
                    f"thread ({why}; call path {chain}) — hand off to a "
                    "spawned thread and return a DeferredReply, or suppress "
                    "with a reason if the wait is provably bounded and "
                    "never feeds back into this pool")))
    return out
