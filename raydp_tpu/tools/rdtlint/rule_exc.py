"""Rule ``exc-contract``: every exception-name string in a cross-process
comparison names a real exception class.

Failures cross the RPC boundary as ``RemoteError.exc_type`` — a *string* —
and the retry/recovery plane keys on it: ``"ObjectLostError"`` routes into
lineage recovery, ``_NO_RETRY_EXC_TYPES`` fails fast, the store client's
``"FileNotFoundError"``/``"KeyError"`` duck-typing decides between a
fresh-lookup retry and a typed loss. Rename (or mistype) one of those
classes and nothing breaks loudly: the comparison just stops matching, and
a no-retry application error quietly becomes a retry storm, or a lost blob
burns the whole retry budget before recovery fires.

The rule collects every comparison of the shape::

    err.exc_type == "Name"            getattr(e, "exc_type", None) in (...)
    err.exc_type in _SOME_CONSTANT    type(e).__name__ == "Name"

(module-level str-tuple/set/frozenset constants are resolved, same as the
knob rule's constant resolution) and validates each name against, in order:

1. a class defined in the linted code whose base chain reaches a builtin
   exception (or an ``*Error``/``*Exception``-named base);
2. a builtin exception (checked via the ``builtins`` module — stdlib, no
   runtime import);
3. the external allowlist in ``rdtlint/config.py``
   (:data:`config.EXC_EXTERNAL_ALLOWLIST` — pyarrow kernels today).

Precision limits: comparisons against names the constant resolution cannot
reach (function parameters, cross-module constants) are skipped; a class
defined in NON-linted code must go through the allowlist. The whole rule is
skipped when ``rpc.py`` (RemoteError's home) is outside the run — without
the wire format the contract does not exist.
"""

from __future__ import annotations

import ast
import builtins
from typing import Dict, List, Optional, Set, Tuple

from raydp_tpu.tools.rdtlint import config
from raydp_tpu.tools.rdtlint.core import Project, SourceFile, Violation

RULE = "exc-contract"

_BUILTIN_EXCS = frozenset(
    name for name in dir(builtins)
    if isinstance(getattr(builtins, name), type)
    and issubclass(getattr(builtins, name), BaseException))


def _is_exc_type_expr(node: ast.AST) -> bool:
    """``x.exc_type`` / ``getattr(x, "exc_type", ...)`` /
    ``type(x).__name__``."""
    if isinstance(node, ast.Attribute):
        if node.attr == "exc_type":
            return True
        if node.attr == "__name__" and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Name) \
                and node.value.func.id == "type":
            return True
        return False
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "getattr" and len(node.args) >= 2:
        k = node.args[1]
        return isinstance(k, ast.Constant) and k.value == "exc_type"
    return False


def _str_constants(src: SourceFile) -> Dict[str, List[Tuple[str, int]]]:
    """NAME -> [(value, line)] for module-level tuple/set/frozenset/list
    constants made of string literals (e.g. ``_NO_RETRY_EXC_TYPES``)."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for node in src.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        val = node.value
        if isinstance(val, ast.Call) and isinstance(val.func, ast.Name) \
                and val.func.id in ("frozenset", "set", "tuple") and val.args:
            val = val.args[0]
        if isinstance(val, (ast.Tuple, ast.Set, ast.List)):
            items = [(e.value, e.lineno) for e in val.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str)]
            if items and len(items) == len(val.elts):
                out[node.targets[0].id] = items
    return out


def _comparand_names(node: ast.AST,
                     consts: Dict[str, List[Tuple[str, int]]]
                     ) -> List[Tuple[str, int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [(node.value, node.lineno)]
    if isinstance(node, (ast.Tuple, ast.Set, ast.List)):
        return [(e.value, e.lineno) for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    if isinstance(node, ast.Name):
        return consts.get(node.id, [])
    return []


def _project_exceptions(project: Project) -> Set[str]:
    """Class names defined in the linted files whose base chain looks like
    an exception (reaches a builtin exception, or any base named *Error /
    *Exception — lenient when a base is imported from outside the run)."""
    bases: Dict[str, List[str]] = {}
    for src in project.files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                names = []
                for b in node.bases:
                    if isinstance(b, ast.Name):
                        names.append(b.id)
                    elif isinstance(b, ast.Attribute):
                        names.append(b.attr)
                bases.setdefault(node.name, names)

    def excish(name: str, seen=()) -> bool:
        if name in _BUILTIN_EXCS:
            return True
        if name in seen:
            return False
        if name.endswith("Error") or name.endswith("Exception") \
                or name == "Warning":
            if name not in bases:
                return True  # imported exception-named base: lenient
        for b in bases.get(name, []):
            if excish(b, seen + (name,)):
                return True
        return False

    return {name for name in bases if excish(name)}


def check(project: Project) -> List[Violation]:
    out: List[Violation] = []
    if project.find_file("rpc.py") is None:
        return out  # no RemoteError in scope: the contract is not checkable
    known = _project_exceptions(project)
    allow = config.EXC_EXTERNAL_ALLOWLIST
    for src in project.files:
        consts = _str_constants(src)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Compare):
                continue
            if not _is_exc_type_expr(node.left):
                continue
            for op, comp in zip(node.ops, node.comparators):
                if not isinstance(op, (ast.Eq, ast.NotEq, ast.In,
                                       ast.NotIn)):
                    continue
                for name, line in _comparand_names(comp, consts):
                    if not name or not name[0].isupper():
                        continue  # not an exception-class shape
                    if name in known or name in _BUILTIN_EXCS \
                            or name in allow:
                        continue
                    out.append(Violation(
                        rule=RULE, path=src.rel, line=line,
                        message=(
                            f"exc_type contract names {name!r}, which is "
                            "neither a linted exception class, a builtin, "
                            "nor allowlisted in rdtlint/config.py — a "
                            "renamed exception here silently demotes this "
                            "comparison (e.g. a no-retry error becomes a "
                            "retry storm)")))
    return out
