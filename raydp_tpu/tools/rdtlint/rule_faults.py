"""Rule ``fault-site-sync``: the fault-injection site namespace cannot
drift between code, parser, docs, and tests.

A fault site exists in four places and they historically drifted by hand:

1. the ``faults.check("<site>", ...)`` / ``faults.apply(rule, "<site>")``
   call sites in the runtime;
2. ``faults.KNOWN_SITES`` — the registry ``parse_spec`` validates an
   ``RDT_FAULTS`` env spec against (a typo'd site used to arm nothing,
   silently);
3. the site table in ``doc/fault_tolerance.md``;
4. the ``RDT_FAULTS`` spec strings chaos tests and benches arm.

The rule cross-checks all four: every code site must be registered and
documented, every registered/documented site must exist in code, and every
site a test spec names must be a real injection point (a chaos test aimed at
a renamed site would silently test nothing — the exact failure mode the
fault plane's loud-parse contract exists to prevent).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from raydp_tpu.tools.rdtlint.core import Project, SourceFile, Violation

RULE = "fault-site-sync"

_ACTIONS = "crash|delay|raise|drop|connloss"
_SPEC_RE = re.compile(
    rf"(?:^|;)\s*([a-z_][\w.]*)\s*:\s*(?:{_ACTIONS})\b")
_DOC_HEADER = re.compile(r"^\|\s*Site\s*\|", re.IGNORECASE)
_DOC_SITE = re.compile(r"^\|\s*`([\w.]+)`\s*\|")


def _faults_aliases(src: SourceFile) -> Set[str]:
    aliases: Set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith(".faults") or a.name == "faults":
                    aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name == "faults":
                    aliases.add(a.asname or a.name)
    return aliases


def _code_sites(project: Project) -> Dict[str, Tuple[str, int]]:
    """site -> (rel, line) of one arming call (``faults.check`` first arg /
    ``faults.apply`` second arg, string literals only)."""
    sites: Dict[str, Tuple[str, int]] = {}
    for src in project.files:
        rel = src.rel.replace(os.sep, "/")
        if rel.startswith(("tests/", "benchmarks/")):
            # tests arm via RDT_FAULTS spec strings (checked below), and
            # test_faults deliberately probes synthetic sites — neither is a
            # code arming site, so a combined package+tests lint run must
            # not register them against KNOWN_SITES / the doc table
            continue
        aliases = _faults_aliases(src)
        if not aliases:
            continue
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in aliases):
                continue
            lit: Optional[ast.AST] = None
            if node.func.attr == "check" and node.args:
                lit = node.args[0]
            elif node.func.attr == "apply" and len(node.args) >= 2:
                lit = node.args[1]
            if isinstance(lit, ast.Constant) and isinstance(lit.value, str) \
                    and lit.value:
                sites.setdefault(lit.value, (src.rel, node.lineno))
    return sites


def _known_sites(src: SourceFile) -> Optional[Tuple[Set[str], int]]:
    """The KNOWN_SITES literal declared in faults.py, with its line."""
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "KNOWN_SITES":
            val = node.value
            if isinstance(val, ast.Call) and val.args:  # frozenset((...))
                val = val.args[0]
            if isinstance(val, (ast.Tuple, ast.List, ast.Set)):
                items = {e.value for e in val.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)}
                return items, node.lineno
    return None


def _doc_sites(path: str) -> Dict[str, int]:
    """site -> line from the `| Site | Fires at | Actions |` table."""
    sites: Dict[str, int] = {}
    in_table = False
    with open(path, "r", encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            if _DOC_HEADER.match(line):
                in_table = True
                continue
            if in_table:
                if not line.startswith("|"):
                    in_table = False
                    continue
                m = _DOC_SITE.match(line)
                if m:
                    sites.setdefault(m.group(1), i)
    return sites


def _spec_strings(src: SourceFile) -> List[Tuple[str, int]]:
    """(text, line) of every string literal in the file that could carry a
    fault spec (f-string constant parts included)."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.append((node.value, node.lineno))
        elif isinstance(node, ast.JoinedStr):
            parts = [v.value for v in node.values
                     if isinstance(v, ast.Constant)
                     and isinstance(v.value, str)]
            if parts:
                out.append(("\x00".join(parts), node.lineno))
    return out


def check(project: Project) -> List[Violation]:
    out: List[Violation] = []
    faults_src = project.find_file("faults.py")
    code_sites = _code_sites(project)

    known: Optional[Set[str]] = None
    known_line = 1
    if faults_src is not None:
        found = _known_sites(faults_src)
        if found is None:
            out.append(Violation(
                rule=RULE, path=faults_src.rel, line=1,
                message=("faults.py declares no KNOWN_SITES registry for "
                         "parse_spec to validate env specs against")))
        else:
            known, known_line = found

    if known is not None:
        for site, (rel, line) in sorted(code_sites.items()):
            if site not in known:
                out.append(Violation(
                    rule=RULE, path=rel, line=line,
                    message=(f"fault site {site!r} is armed here but "
                             "missing from faults.KNOWN_SITES — an "
                             "RDT_FAULTS spec naming it would be "
                             "rejected")))
        for site in sorted(known - set(code_sites)):
            if code_sites:  # whole-package runs only
                out.append(Violation(
                    rule=RULE, path=faults_src.rel, line=known_line,
                    message=(f"KNOWN_SITES entry {site!r} has no "
                             "faults.check() call site in the linted "
                             "code — stale registry entry")))

    # ---- doc table --------------------------------------------------------
    doc_path = os.path.join(project.root, "doc", "fault_tolerance.md")
    if code_sites and os.path.isdir(os.path.join(project.root, "doc")):
        if not os.path.exists(doc_path):
            out.append(Violation(
                rule=RULE, path="doc/fault_tolerance.md", line=1,
                message="fault-site doc table file missing"))
        else:
            doc = _doc_sites(doc_path)
            for site, (rel, line) in sorted(code_sites.items()):
                if site not in doc:
                    out.append(Violation(
                        rule=RULE, path=rel, line=line,
                        message=(f"fault site {site!r} is missing from the "
                                 "site table in doc/fault_tolerance.md")))
            for site, line in sorted(doc.items()):
                if site not in code_sites:
                    out.append(Violation(
                        rule=RULE, path="doc/fault_tolerance.md", line=line,
                        message=(f"documented fault site {site!r} has no "
                                 "faults.check() call site in code")))

    # ---- test / bench specs ----------------------------------------------
    if code_sites:
        valid = set(code_sites) | (known or set())
        for subdir in ("tests", "benchmarks"):
            for src in project.extra_files(subdir):
                for text, line in _spec_strings(src):
                    for m in _SPEC_RE.finditer(text):
                        site = m.group(1)
                        if site not in valid:
                            out.append(Violation(
                                rule=RULE, path=src.rel, line=line,
                                message=(
                                    f"RDT_FAULTS spec names site {site!r} "
                                    "which no code arms — this schedule "
                                    "would inject nothing")))
    return out
