"""Rule ``knob-registry``: every ``RDT_*`` environment knob is declared in
``raydp_tpu/knobs.py``, read through it, and documented from it.

Four checks:

1. **No scattered reads** — a direct ``os.environ`` / ``os.getenv`` read of
   an ``RDT_*`` name outside ``knobs.py`` is a violation (reads through
   module-level string constants are resolved). Env *writes* are fine: the
   head/agents inject framework knobs into child environments by design.
2. **No unregistered names** — ``knobs.get("RDT_X")`` (and ``get_raw`` /
   ``require``) with a name missing from the registry.
3. **No import-time caching of per-action knobs** — a per-action knob read
   at module scope, class scope, or in a function default is pinned to
   whatever the process first saw; this is the PR 3 ``RDT_FAULTS`` re-arm
   bug class. (Process-start knobs MAY be read at import.) Registered knobs
   that no package code references at all are flagged too (registry drift).
4. **Docs are generated** — the knob tables in ``doc/etl.md`` /
   ``doc/training.md`` / ``doc/dev_lint.md`` must equal the registry's
   rendered output (``python -m raydp_tpu.knobs --write-docs`` regenerates).
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, List, Optional, Set, Tuple

from raydp_tpu.tools.rdtlint.core import (
    Project, SourceFile, Violation, marker_block_violation)

RULE = "knob-registry"

_KNOB_FUNCS = ("get", "get_raw", "require")


def _load_registry(path: str):
    """Load knobs.py standalone (it is stdlib-only by contract) without
    importing the raydp_tpu runtime."""
    import sys

    spec = importlib.util.spec_from_file_location("_rdtlint_knobs", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclass decorators resolve the defining module through sys.modules
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)  # type: ignore[union-attr]
    finally:
        sys.modules.pop(spec.name, None)
    return mod


def _module_constants(src: SourceFile) -> Dict[str, str]:
    """NAME -> literal for module/class-level ``NAME = "RDT_..."``."""
    consts: Dict[str, str] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value
    return consts


def _is_environ(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "environ"
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name) and node.value.id == "os")


def _env_read_key(node: ast.AST) -> Optional[ast.AST]:
    """The key expression when ``node`` READS the environment."""
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in ("get", "setdefault") \
                and _is_environ(f.value) and node.args:
            return node.args[0]
        if isinstance(f, ast.Attribute) and f.attr == "getenv" \
                and isinstance(f.value, ast.Name) and f.value.id == "os" \
                and node.args:
            return node.args[0]
        if isinstance(f, ast.Name) and f.id == "getenv" and node.args:
            return node.args[0]
    if isinstance(node, ast.Subscript) and _is_environ(node.value) \
            and isinstance(node.ctx, ast.Load):
        return node.slice
    return None


def _resolve_key(key: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    if isinstance(key, ast.Constant) and isinstance(key.value, str):
        return key.value
    if isinstance(key, ast.Name):
        return consts.get(key.id)
    return None


def _default_nodes(src: SourceFile) -> Set[int]:
    """ids of AST nodes inside function-default expressions (evaluated at
    def time, i.e. import time for top-level functions)."""
    out: Set[int] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in list(node.args.defaults) + \
                    [d for d in node.args.kw_defaults if d is not None]:
                for sub in ast.walk(d):
                    out.add(id(sub))
    return out


def _is_import_time(src: SourceFile, node: ast.AST,
                    defaults: Set[int]) -> bool:
    funcs = [a for a in src.ancestors(node)
             if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda))]
    if not funcs:
        return True  # module or class scope
    # inside a default of the outermost enclosing function, and that
    # function is itself defined at import time
    return id(node) in defaults and len(funcs) == 1


def _knob_aliases(src: SourceFile) -> Set[str]:
    """Local names bound to the knobs module in this file."""
    aliases: Set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("knobs") or a.name == "knobs":
                    aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name == "knobs":
                    aliases.add(a.asname or a.name)
    return aliases


def check(project: Project) -> List[Violation]:
    out: List[Violation] = []
    knobs_src = project.find_file("knobs.py")
    registry = None
    registry_mod = None
    if knobs_src is not None:
        try:
            registry_mod = _load_registry(knobs_src.path)
            registry = registry_mod.KNOBS
        except Exception as e:  # noqa: BLE001 - a broken registry IS a finding
            out.append(Violation(
                rule=RULE, path=knobs_src.rel, line=1,
                message=f"could not load knob registry: {e!r}"))

    referenced: Set[str] = set()
    for src in project.files:
        if knobs_src is not None and src.path == knobs_src.path:
            continue
        consts = _module_constants(src)
        defaults = _default_nodes(src)
        aliases = _knob_aliases(src)
        for node in ast.walk(src.tree):
            # ---- direct environment reads -------------------------------
            key = _env_read_key(node)
            if key is not None:
                name = _resolve_key(key, consts)
                if name and name.startswith("RDT_"):
                    referenced.add(name)
                    out.append(Violation(
                        rule=RULE, path=src.rel, line=node.lineno,
                        message=(
                            f"direct environment read of {name} — go "
                            "through raydp_tpu.knobs (get/require) so the "
                            "registry stays the single source of truth")))
                continue
            # ---- knobs API calls ----------------------------------------
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _KNOB_FUNCS \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in aliases and node.args:
                name = _resolve_key(node.args[0], consts)
                if name is None:
                    continue
                referenced.add(name)
                if registry is not None and name not in registry:
                    out.append(Violation(
                        rule=RULE, path=src.rel, line=node.lineno,
                        message=(f"knobs.{node.func.attr}({name!r}): not "
                                 "declared in raydp_tpu/knobs.py")))
                elif registry is not None \
                        and registry[name].scope == "per-action" \
                        and _is_import_time(src, node, defaults):
                    out.append(Violation(
                        rule=RULE, path=src.rel, line=node.lineno,
                        message=(
                            f"{name} is a per-action knob but is read at "
                            "import time — the value pins to whatever this "
                            "process first saw (the RDT_FAULTS re-arm bug "
                            "class); read it inside the function that uses "
                            "it")))
            # ---- plain string references (for the drift check) ----------
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value.startswith("RDT_"):
                parent = src.parent(node)
                if not isinstance(parent, ast.Expr):  # skip docstrings
                    referenced.add(node.value)

    # ---- registry drift: declared but never referenced by package code ---
    if registry is not None and knobs_src is not None \
            and any(f.path != knobs_src.path for f in project.files):
        for name in registry:
            if name not in referenced:
                out.append(Violation(
                    rule=RULE, path=knobs_src.rel, line=1,
                    message=(f"{name} is declared in the registry but no "
                             "linted code references it — dead knob or "
                             "missed migration")))

    # ---- generated doc tables -------------------------------------------
    if registry_mod is not None and os.path.isdir(
            os.path.join(project.root, "doc")):
        for rel, category in registry_mod.DOC_TABLES:
            path = os.path.join(project.root, rel)
            if not os.path.exists(path):
                out.append(Violation(
                    rule=RULE, path=rel, line=1,
                    message="knob-table doc file missing"))
                continue
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
            begin, end = registry_mod.table_markers(category)
            v = marker_block_violation(
                RULE, rel, text, begin, end,
                registry_mod.render_block(category), "knob",
                "python -m raydp_tpu.knobs --write-docs")
            if v is not None:
                out.append(v)
    return out
