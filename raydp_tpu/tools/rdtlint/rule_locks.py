"""Rule ``lock-discipline``: attributes declared ``# guarded-by: <lock>``
must be accessed under that lock.

Convention (doc/dev_lint.md):

- Declaration — on the attribute's initialization line::

      self._blocks = {}  # guarded-by: _lock

  declares that every read/write of ``self._blocks`` anywhere in the class
  must sit lexically inside ``with self._lock:`` (``__init__`` itself is
  exempt: construction happens-before sharing).

- A method that RUNS with the lock held (the ``*_locked`` helper pattern)
  declares it on its ``def`` line::

      def _resp_locked(self, ...):  # guarded-by: _lock

  making its whole body count as guarded — the callers' ``with`` blocks are
  the enforcement boundary.

Only annotated attributes are checked: adoption is incremental, seeded
across the four concurrency-heavy runtime modules where instance state is
mutated from thread targets, deferred-reply bodies, and late-result
callbacks. The check is lexical (no alias or happens-before analysis);
deliberate lock-free reads carry an ``allow[lock-discipline]`` with the
reason they are safe.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from raydp_tpu.tools.rdtlint.core import Project, SourceFile, Violation

RULE = "lock-discipline"


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _find_guards(src: SourceFile, cls: ast.ClassDef) -> Dict[str, str]:
    """attr -> guard name, from ``self.X = ...  # guarded-by: _lock`` lines
    anywhere in the class body (typically ``__init__``)."""
    guards: Dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            # any line of the assignment (a wrapped initializer may carry
            # the comment on a continuation line), or a comment-only line
            # directly above when the statement has no room
            guard = None
            for line in range(node.lineno,
                              (node.end_lineno or node.lineno) + 1):
                guard = src.guarded_by(line)
                if guard:
                    break
            guard = guard or src.guarded_by(node.lineno, allow_above=True)
            if not guard:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr:
                    guards[attr] = guard
    return guards


def _enclosing_function(src: SourceFile, node: ast.AST,
                        cls: ast.ClassDef) -> Optional[ast.AST]:
    """The METHOD of ``cls`` lexically containing ``node`` (the outermost
    function between the node and the class body)."""
    method = None
    for anc in src.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            method = anc
        if anc is cls:
            return method
    return None


def _is_guarded(src: SourceFile, node: ast.AST, guard: str,
                cls: ast.ClassDef) -> bool:
    for anc in src.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                if _self_attr(item.context_expr) == guard:
                    return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # method-level "runs with the lock held" annotation
            if src.guarded_by(anc.lineno) == guard:
                return True
        if anc is cls:
            return False
    return False


def check(project: Project) -> List[Violation]:
    out: List[Violation] = []
    for src in project.files:
        for cls in [n for n in ast.walk(src.tree)
                    if isinstance(n, ast.ClassDef)]:
            guards = _find_guards(src, cls)
            if not guards:
                continue
            for node in ast.walk(cls):
                attr = _self_attr(node)
                if attr is None or attr not in guards:
                    continue
                guard = guards[attr]
                method = _enclosing_function(src, node, cls)
                if method is None or method.name == "__init__":
                    continue  # class body / construction happens-before
                if src.guarded_by(node.lineno, allow_above=True) is not None:
                    continue  # the declaration line itself
                if _is_guarded(src, node, guard, cls):
                    continue
                out.append(Violation(
                    rule=RULE, path=src.rel, line=node.lineno,
                    message=(
                        f"self.{attr} ({cls.name}) is declared guarded-by "
                        f"self.{guard} but is accessed in {method.name}() "
                        f"outside `with self.{guard}:`")))
    return out
