"""Rule ``rpc-surface``: every literal cross-process call resolves.

The control plane is stringly typed by design (one wire format, getattr
dispatch — ``runtime/rpc.py``), which makes three drifts invisible until the
exact hop fires at run time, inside a ``RemoteError``:

1. **typo'd / renamed method** — ``head.call("lokup", ...)`` is an
   AttributeError on the server;
2. **arity drift** — a server signature gained a required parameter and some
   call site still passes the old shape (TypeError on the server);
3. **proxy drift** — the head proxies the store table verbatim
   (``HeadService.store_<m>`` → ``ObjectStoreServer.<m>``); a store method
   the client drives through ``self._server.<m>`` without a matching proxy
   works in-process (the head holds the real server) and explodes only in a
   client-mode driver or actor process, where ``self._server`` is the
   ``StoreTableProxy``.

Checks, against the AST-built surface map (:mod:`surfaces`):

- every ``<recv>.call("name", ...)`` / ``<recv>.submit("name", ...)`` with a
  literal method name resolves on the receiver's surface
  (:data:`config.RPC_RECEIVER_SURFACES`; unmapped receivers check against
  the union of all surfaces) with compatible arity (``timeout=`` excluded —
  RpcClient consumes it);
- no literal call targets an underscore method (MethodDispatcher refuses
  them) except the ``__rdt_*`` actor intrinsics;
- head proxy completeness both ways: every store method the client calls
  has a ``store_<m>`` proxy, and every ``store_<m>`` proxy forwards to a
  real, same-named store server method;
- the generated RPC-surface table in ``doc/dev_lint.md`` matches the map
  (``python -m raydp_tpu.tools.rdtlint --write-rpc-docs`` regenerates).

Precision limits: calls whose method name is a variable (the StoreTableProxy
forwarders) and attribute-style actor calls (``handle.run_task(...)``)
create no check; a receiver the map cannot name falls back to the union, so
a method existing on ANY surface passes.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from raydp_tpu.tools.rdtlint import config, surfaces
from raydp_tpu.tools.rdtlint.core import (
    Project, Violation, marker_block_violation)

RULE = "rpc-surface"

_CALL_ATTRS = ("call", "submit")


def _receiver_name(recv: ast.AST) -> Optional[str]:
    """The name the receiver map keys on: the variable, its attribute, or
    the function that produced it (``self._peer(addr).call``)."""
    if isinstance(recv, ast.Name):
        return recv.id
    if isinstance(recv, ast.Attribute):
        return recv.attr
    if isinstance(recv, ast.Call):
        return _receiver_name(recv.func)
    return None


def _surface_tags(recv_name: Optional[str], smap: surfaces.SurfaceMap
                  ) -> Optional[List[str]]:
    """Surfaces to resolve against, or None to skip the site (a mapped tag
    whose server class is outside this lint run)."""
    tag = config.RPC_RECEIVER_SURFACES.get(recv_name or "")
    if tag is None or tag == "*":
        tags = [t for t in smap.surfaces if smap.has_surface(t)]
        return tags or None
    if not smap.has_surface(tag):
        return None  # targeted run without the server class: unknowable
    return [tag]


def _check_site(src, node: ast.Call, smap: surfaces.SurfaceMap,
                out: List[Violation]) -> None:
    meth_node = node.args[0]
    method = meth_node.value
    recv_name = _receiver_name(node.func.value)

    if method.startswith("_") \
            and method not in config.RPC_INTRINSIC_METHODS:
        out.append(Violation(
            rule=RULE, path=src.rel, line=node.lineno,
            message=(f"remote call targets underscore method {method!r} — "
                     "MethodDispatcher refuses it; this site can only ever "
                     "raise AttributeError inside a RemoteError")))
        return
    if method in config.RPC_INTRINSIC_METHODS:
        return  # served by _ActorServer before dispatch, any arity

    tags = _surface_tags(recv_name, smap)
    if tags is None:
        return
    candidates = [smap.methods(t)[method] for t in tags
                  if method in smap.methods(t)]
    if not candidates:
        where = (f"surface {tags[0]!r}" if len(tags) == 1
                 else "any linted RPC surface")
        out.append(Violation(
            rule=RULE, path=src.rel, line=node.lineno,
            message=(f"remote call {method!r} resolves on no method of "
                     f"{where} — a typo'd or renamed RPC is a runtime "
                     "AttributeError inside a RemoteError")))
        return
    errors = []
    for sig in candidates:
        err = sig.check_call(list(node.args[1:]), list(node.keywords))
        if err is None:
            return
        errors.append(err)
    out.append(Violation(
        rule=RULE, path=src.rel, line=node.lineno,
        message=f"remote call {method!r}: {errors[0]}"))


def _check_proxy_completeness(project: Project,
                              smap: surfaces.SurfaceMap,
                              out: List[Violation]) -> None:
    client = smap.class_defs.get(config.RPC_STORE_CLIENT_CLASS)
    server = smap.class_defs.get(config.RPC_STORE_SERVER_CLASS)
    head = smap.class_defs.get(config.RPC_HEAD_SERVICE_CLASS)
    if client is None or server is None or head is None:
        return  # targeted run: the triple is not in scope
    prefix = config.RPC_STORE_PROXY_PREFIX
    server_methods = {n.name for n in server[1].body
                      if isinstance(n, ast.FunctionDef)}
    head_methods = {n.name: n for n in head[1].body
                    if isinstance(n, ast.FunctionDef)}

    # every client-driven store method has a head proxy and a real target
    src, cls = client
    seen = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Attribute)
                and isinstance(node.func.value.value, ast.Name)
                and node.func.value.value.id == "self"
                and node.func.value.attr == "_server"):
            continue
        m = node.func.attr
        if m in seen:
            continue
        seen.add(m)
        if m not in server_methods:
            out.append(Violation(
                rule=RULE, path=src.rel, line=node.lineno,
                message=(f"{config.RPC_STORE_CLIENT_CLASS} calls "
                         f"self._server.{m}() but "
                         f"{config.RPC_STORE_SERVER_CLASS} defines no such "
                         "method")))
        if prefix + m not in head_methods:
            out.append(Violation(
                rule=RULE, path=src.rel, line=node.lineno,
                message=(f"store method {m!r} is driven through "
                         "self._server but the head has no "
                         f"{prefix}{m} proxy — works in-process, "
                         "AttributeError inside a RemoteError for every "
                         "actor/client-mode process (StoreTableProxy "
                         "forwards it to the head)")))

    # every store_* proxy forwards to a real, same-named server method
    hsrc, _hcls = head
    for name, fn in head_methods.items():
        if not name.startswith(prefix) or name.startswith("_"):
            continue
        target = name[len(prefix):]
        forwards: List[str] = [
            sub.func.attr for sub in ast.walk(fn)
            if isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and isinstance(sub.func.value, ast.Attribute)
            and sub.func.value.attr == "store_server"]
        if target not in server_methods:
            out.append(Violation(
                rule=RULE, path=hsrc.rel, line=fn.lineno,
                message=(f"head proxy {name} forwards to "
                         f"{config.RPC_STORE_SERVER_CLASS}.{target} which "
                         "does not exist — dead proxy or renamed server "
                         "method")))
        elif forwards and target not in forwards:
            out.append(Violation(
                rule=RULE, path=hsrc.rel, line=fn.lineno,
                message=(f"head proxy {name} forwards to store_server."
                         f"{forwards[0]} but its name promises {target!r} — "
                         "StoreTableProxy routes by name, so this proxy "
                         "serves the wrong method")))


def _check_doc_table(project: Project, smap: surfaces.SurfaceMap,
                     out: List[Violation]) -> None:
    """Mirror of the knob-table drift fence: only meaningful on a run that
    sees the real surfaces (≥ 3 configured surface tags present)."""
    present = sum(1 for tag in config.RPC_SURFACE_CLASSES
                  if smap.has_surface(tag))
    doc_rel = "doc/dev_lint.md"
    path = os.path.join(project.root, doc_rel)
    if present < 3 or not os.path.exists(path):
        return
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    v = marker_block_violation(
        RULE, doc_rel, text, surfaces.RPC_TABLE_BEGIN,
        surfaces.RPC_TABLE_END, surfaces.render_block(smap), "RPC-surface",
        "python -m raydp_tpu.tools.rdtlint --write-rpc-docs")
    if v is not None:
        out.append(v)


def check(project: Project) -> List[Violation]:
    smap = surfaces.build(project)
    out: List[Violation] = []
    if smap.surfaces:
        for src in project.files:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _CALL_ATTRS \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    _check_site(src, node, smap, out)
    _check_proxy_completeness(project, smap, out)
    _check_doc_table(project, smap, out)
    return out
