"""Rule ``step-registry``: every ref-carrying task step is registered with
the lineage-recovery plane.

The bug class this encodes re-surfaced in PRs 3, 4, 6, and 7: a new ``Step``
subclass carries ``ObjectRef``s (or a nested ``Task``, or a streaming
source) and must be hand-registered in the recovery surgery
(``tasks._patch_step_refs`` / ``tasks.task_input_ids``) and — when it
carries a stream — the stream plane (``tasks.stream_sources_of`` /
``tasks.resolve_stream_sources``). Forgetting any of them is a
lineage-recovery hole that stays invisible until a blob dies under exactly
that step ("patch_task_refs learns RangeRefSource / BroadcastJoinStep /
StreamingRangeSource" — each a review-caught re-fix).

The registry is the ``# carries-refs: attr, attr`` annotation on the class
line in ``etl/tasks.py``; the rule keeps it honest in both directions and
then checks the handlers:

1. **declaration sync** — a ``Step`` subclass whose dataclass fields are
   typed with ``ObjectRef`` / ``Task`` / the streaming source class must
   declare exactly those attributes; an annotation naming anything else (or
   a carrying field left undeclared) is drift.
2. **ref/task attrs** — the class is isinstance-handled in every
   :data:`config.STEP_REF_HANDLERS` function, and each declared attr is
   touched inside one of its branches (attribute access or a
   ``dataclasses.replace(..., attr=...)`` keyword).
3. **stream attrs** (and nested-task attrs) — handled in every
   :data:`config.STEP_STREAM_HANDLERS` function, by isinstance or by a
   ``getattr(step, "<attr>", ...)`` literal.
4. **result-ref keys** — the executor writes ref-valued task results only
   under :data:`config.STEP_RESULT_REF_KEYS`, and ``engine._result_refs``
   harvests every one (the single extraction shared by the lineage ledger,
   regeneration, and frees — a key missing there orphans blobs on every
   failed stage).
5. **stream buckets** — each :data:`config.STEP_STREAM_BUCKET_FUNCS`
   function in ``engine.py`` isinstance-handles ``_StreamBucket`` (the
   pipelined stage's placeholder: locality weighting, reduce-source
   construction, stream-key tagging).

Precision limits: carrier inference reads dataclass field annotations — a
ref hidden in an untyped container (``List[Any]``) is invisible, so keep
ref-bearing fields typed; attr-touch checking is per-isinstance-branch but
does not prove the patch is *correct*, only present.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from raydp_tpu.tools.rdtlint import config
from raydp_tpu.tools.rdtlint.core import Project, SourceFile, Violation

RULE = "step-registry"

_CARRIES_RE = re.compile(r"#\s*carries-refs:\s*([\w,\s]+)")

_REF_WORD = re.compile(r"\bObjectRef\b")
_TASK_WORD = re.compile(r"\bTask\b")


@dataclass
class StepClass:
    name: str
    line: int
    ref_attrs: Set[str] = field(default_factory=set)      # ObjectRef-typed
    task_attrs: Set[str] = field(default_factory=set)     # nested Task
    stream_attrs: Set[str] = field(default_factory=set)   # streaming source
    declared: Optional[Set[str]] = None                   # carries-refs attrs
    declared_line: int = 0

    @property
    def inferred(self) -> Set[str]:
        return self.ref_attrs | self.task_attrs | self.stream_attrs


def _annotation_kind(ann: ast.AST) -> Optional[str]:
    try:
        text = ast.unparse(ann)
    except Exception:  # noqa: BLE001 - unparse is best-effort
        return None
    if _REF_WORD.search(text):
        return "ref"
    if re.search(rf"\b{config.STEP_STREAM_SOURCE_CLASS}\b", text):
        return "stream"
    if _TASK_WORD.search(text):
        return "task"
    return None


def _declared_attrs(src: SourceFile, cls: ast.ClassDef
                    ) -> Tuple[Optional[Set[str]], int]:
    """The ``# carries-refs:`` annotation on the class line, or a
    comment-only line directly above the first decorator/class line."""
    first = min([cls.lineno] + [d.lineno for d in cls.decorator_list])
    for cand in (cls.lineno, first - 1):
        c = src.comments.get(cand)
        if not c or (cand != cls.lineno and not src.comment_only_line(cand)):
            continue
        m = _CARRIES_RE.search(c)
        if m:
            attrs = {a.strip() for a in m.group(1).split(",") if a.strip()}
            return attrs, cand
    return None, 0


def _step_classes(src: SourceFile) -> Dict[str, StepClass]:
    """Every subclass of ``Step`` in the tasks file (transitive within the
    file), with carrier attrs inferred from field annotations."""
    classes: Dict[str, ast.ClassDef] = {
        n.name: n for n in src.tree.body if isinstance(n, ast.ClassDef)}
    bases: Dict[str, List[str]] = {
        name: [b.id for b in node.bases if isinstance(b, ast.Name)]
        for name, node in classes.items()}

    def is_step(name: str, seen=()) -> bool:
        if name == "Step":
            return True
        if name in seen or name not in bases:
            return False
        return any(is_step(b, seen + (name,)) for b in bases[name])

    out: Dict[str, StepClass] = {}
    for name, node in classes.items():
        if name == "Step" or not is_step(name):
            continue
        sc = StepClass(name=name, line=node.lineno)
        for item in node.body:
            if isinstance(item, ast.AnnAssign) \
                    and isinstance(item.target, ast.Name):
                kind = _annotation_kind(item.annotation)
                if kind == "ref":
                    sc.ref_attrs.add(item.target.id)
                elif kind == "task":
                    sc.task_attrs.add(item.target.id)
                elif kind == "stream":
                    sc.stream_attrs.add(item.target.id)
        sc.declared, sc.declared_line = _declared_attrs(src, node)
        out[name] = sc
    return out


def _isinstance_branches(fn: ast.FunctionDef
                         ) -> List[Tuple[Set[str], Set[str]]]:
    """(class names, touched attrs) per ``isinstance`` branch: attrs are
    attribute accesses plus call keywords (``dataclasses.replace(step,
    right_parts=...)``) in the branch body."""
    out: List[Tuple[Set[str], Set[str]]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        names: Set[str] = set()
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)\
                    and sub.func.id == "isinstance" and len(sub.args) == 2:
                t = sub.args[1]
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                names |= {e.id for e in elts if isinstance(e, ast.Name)}
        if not names:
            continue
        attrs: Set[str] = set()
        for sub in node.body:
            for n in ast.walk(sub):
                if isinstance(n, ast.Attribute):
                    attrs.add(n.attr)
                elif isinstance(n, ast.Call):
                    attrs |= {kw.arg for kw in n.keywords if kw.arg}
        out.append((names, attrs))
    return out


def _getattr_literals(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "getattr" and len(node.args) >= 2 \
                and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            out.add(node.args[1].value)
    return out


def _module_functions(src: SourceFile) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in src.tree.body
            if isinstance(n, ast.FunctionDef)}


def _class_functions(src: SourceFile) -> Dict[str, ast.FunctionDef]:
    out: Dict[str, ast.FunctionDef] = {}
    for node in src.tree.body:
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    out.setdefault(item.name, item)
    return out


def _check_handler(sc: StepClass, attrs: Set[str], fn_name: str,
                   fn: Optional[ast.FunctionDef], tasks_rel: str,
                   allow_getattr: bool, out: List[Violation]) -> None:
    if fn is None:
        return  # absence of the handler itself is reported once, not per class
    branches = _isinstance_branches(fn)
    mine = [(names, touched) for names, touched in branches
            if sc.name in names]
    if not mine:
        if allow_getattr and attrs:
            gets = _getattr_literals(fn)
            if all(a in gets for a in attrs):
                return  # duck-typed handling (getattr on every stream attr)
        out.append(Violation(
            rule=RULE, path=tasks_rel, line=sc.line,
            message=(f"step class {sc.name} carries refs "
                     f"({', '.join(sorted(attrs))}) but is not handled in "
                     f"{fn_name}() — a lost blob under this step cannot be "
                     "recovered (the PR 6 BroadcastJoinStep regression "
                     "shape)")))
        return
    touched = set().union(*(t for _, t in mine))
    for a in sorted(attrs - touched):
        out.append(Violation(
            rule=RULE, path=tasks_rel, line=sc.line,
            message=(f"{fn_name}() handles {sc.name} but never touches its "
                     f"declared carrier attribute {a!r} — the registry says "
                     "this attr carries refs; patch it or fix the "
                     "declaration")))


def _check_result_keys(engine_src: SourceFile, exec_src: SourceFile,
                       out: List[Violation]) -> None:
    keys = config.STEP_RESULT_REF_KEYS
    fns = _module_functions(engine_src)
    fns.update(_class_functions(engine_src))
    rref = fns.get("_result_refs")
    if rref is not None:
        read = {n.value for n in ast.walk(rref)
                if isinstance(n, ast.Constant) and isinstance(n.value, str)}
        for k in keys:
            if k not in read:
                out.append(Violation(
                    rule=RULE, path=engine_src.rel, line=rref.lineno,
                    message=(f"engine._result_refs() never reads result key "
                             f"{k!r} — outputs under it escape the lineage "
                             "ledger, regeneration, AND the failed-stage "
                             "free (orphan leak)")))
    else:
        out.append(Violation(
            rule=RULE, path=engine_src.rel, line=1,
            message=("engine.py defines no _result_refs() — the single "
                     "output-ref extraction the ledger/regenerate/free "
                     "plane shares is gone")))

    run_fn = _class_functions(exec_src).get("_run_task_obj")
    if run_fn is None:
        return
    refish: Dict[str, int] = {}
    for node in ast.walk(run_fn):
        pairs: List[Tuple[ast.AST, ast.AST, int]] = []
        if isinstance(node, ast.Dict):
            pairs = [(k, v, node.lineno)
                     for k, v in zip(node.keys, node.values)
                     if k is not None]
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Subscript):
            pairs = [(node.targets[0].slice, node.value, node.lineno)]
        for k, v, line in pairs:
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            if _value_is_refish(v):
                refish.setdefault(k.value, line)
    for k, line in sorted(refish.items()):
        if k not in keys:
            out.append(Violation(
                rule=RULE, path=exec_src.rel, line=line,
                message=(f"executor task result carries refs under key "
                         f"{k!r}, which is not in the registered "
                         f"result-ref keys {tuple(keys)} — "
                         "engine._result_refs() will never free or "
                         "re-ledger it (register the key in "
                         "rdtlint/config.py AND read it there)")))


def _value_is_refish(v: ast.AST) -> bool:
    """Does a result-value expression smell like store refs? Names/attrs
    called ``ref``/``refs`` (or ``*_ref``/``*_refs``) and direct put calls."""
    for node in ast.walk(v):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and (name in ("ref", "refs") or name.endswith("_ref")
                     or name.endswith("_refs")
                     or name in ("put_arrow", "put_raw", "put",
                                 "put_arrow_many", "put_raw_many")):
            return True
    return False


def _check_stream_buckets(engine_src: SourceFile,
                          out: List[Violation]) -> None:
    fns = _module_functions(engine_src)
    fns.update(_class_functions(engine_src))
    for fn_name in config.STEP_STREAM_BUCKET_FUNCS:
        fn = fns.get(fn_name)
        if fn is None:
            out.append(Violation(
                rule=RULE, path=engine_src.rel, line=1,
                message=(f"engine.py defines no {fn_name}() — the "
                         "_StreamBucket handling registry in "
                         "rdtlint/config.py is stale")))
            continue
        handles = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
            and n.func.id == "isinstance" and len(n.args) == 2
            and any(isinstance(e, ast.Name) and e.id == "_StreamBucket"
                    for e in (n.args[1].elts
                              if isinstance(n.args[1], ast.Tuple)
                              else [n.args[1]]))
            for n in ast.walk(fn))
        if not handles:
            out.append(Violation(
                rule=RULE, path=engine_src.rel, line=fn.lineno,
                message=(f"{fn_name}() no longer isinstance-handles "
                         "_StreamBucket — a pipelined stage's bucket "
                         "placeholder would fall through the plain-ref "
                         "path (wrong locality / broken reduce source)")))


def check(project: Project) -> List[Violation]:
    out: List[Violation] = []
    tasks_src = project.find_file("etl/tasks.py") \
        or project.find_file("tasks.py")
    if tasks_src is None:
        return out

    steps = _step_classes(tasks_src)
    fns = _module_functions(tasks_src)

    # declaration sync (both directions)
    for sc in steps.values():
        if sc.name == config.STEP_STREAM_SOURCE_CLASS:
            continue  # the stream source itself carries no ref fields
        if sc.inferred and sc.declared is None:
            out.append(Violation(
                rule=RULE, path=tasks_src.rel, line=sc.line,
                message=(f"step class {sc.name} has ref-carrying fields "
                         f"({', '.join(sorted(sc.inferred))}) but no "
                         "`# carries-refs:` declaration on its class line "
                         "— declare them so the recovery-handler checks "
                         "cover this class")))
            continue
        if sc.declared is None:
            continue
        missing = sc.inferred - sc.declared
        extra = sc.declared - sc.inferred
        for a in sorted(missing):
            out.append(Violation(
                rule=RULE, path=tasks_src.rel, line=sc.declared_line,
                message=(f"{sc.name}: field {a!r} is typed as a carrier "
                         "but missing from its # carries-refs: "
                         "declaration")))
        for a in sorted(extra):
            out.append(Violation(
                rule=RULE, path=tasks_src.rel, line=sc.declared_line,
                message=(f"{sc.name}: # carries-refs: names {a!r} but no "
                         "field of that name carries ObjectRef/Task/"
                         "stream types — stale declaration")))

    # handler registration for declared carriers
    for fn_name in config.STEP_REF_HANDLERS:
        if fn_name not in fns:
            out.append(Violation(
                rule=RULE, path=tasks_src.rel, line=1,
                message=(f"tasks.py defines no {fn_name}() — the lineage "
                         "ref-surgery registry is gone")))
    for fn_name in config.STEP_STREAM_HANDLERS:
        if fn_name not in fns:
            out.append(Violation(
                rule=RULE, path=tasks_src.rel, line=1,
                message=(f"tasks.py defines no {fn_name}() — the stream "
                         "routing/resolution registry is gone")))
    for sc in steps.values():
        declared = sc.declared if sc.declared is not None else set()
        ref_like = (declared & (sc.ref_attrs | sc.task_attrs))
        stream_like = (declared & sc.stream_attrs) | sc.task_attrs & declared
        if ref_like:
            for fn_name in config.STEP_REF_HANDLERS:
                _check_handler(sc, ref_like, fn_name, fns.get(fn_name),
                               tasks_src.rel, allow_getattr=False, out=out)
        if stream_like:
            for fn_name in config.STEP_STREAM_HANDLERS:
                _check_handler(sc, stream_like, fn_name, fns.get(fn_name),
                               tasks_src.rel, allow_getattr=True, out=out)

    # the stream source class itself must be routed and resolvable
    if config.STEP_STREAM_SOURCE_CLASS in steps:
        ssc = steps[config.STEP_STREAM_SOURCE_CLASS]
        for fn_name in config.STEP_STREAM_HANDLERS:
            fn = fns.get(fn_name)
            if fn is None:
                continue
            handled = any(ssc.name in names
                          for names, _ in _isinstance_branches(fn))
            if not handled:
                out.append(Violation(
                    rule=RULE, path=tasks_src.rel, line=ssc.line,
                    message=(f"{fn_name}() does not isinstance-handle "
                             f"{ssc.name} — streamed reads would not be "
                             "routed onto stream threads / resolved into "
                             "concrete ranges for recipes")))

    # engine/executor side (skipped on targeted runs without those files)
    engine_src = project.find_file("etl/engine.py") \
        or project.find_file("engine.py")
    exec_src = project.find_file("etl/executor.py") \
        or project.find_file("executor.py")
    if engine_src is not None and exec_src is not None:
        _check_result_keys(engine_src, exec_src, out)
    if engine_src is not None and any(
            isinstance(n, ast.ClassDef) and n.name == "_StreamBucket"
            for n in engine_src.tree.body):
        _check_stream_buckets(engine_src, out)
    return out
