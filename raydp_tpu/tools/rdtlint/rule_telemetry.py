"""Rule ``telemetry-registry``: every literal span, metric, and
flight-recorder event name is declared in ``raydp_tpu/metrics.py``, used
with the right kind, and the generated tables in ``doc/observability.md``
are fresh.

Five checks:

1. **Span names** — a literal first argument of ``profiler.trace(...)`` /
   ``profiler.open_span(...)`` must be a registered span name (or fall
   under a registered dynamic family prefix like ``task:``). F-string span
   names are skipped — the registry documents their family via the prefix
   rows.
2. **Metric names + kinds** — ``metrics.inc`` / ``metrics.set_gauge`` /
   ``metrics.observe`` with a literal name must name a registered metric of
   the matching kind (counter / gauge / histogram).
3. **Event kinds** — ``metrics.record_event`` with a literal kind must name
   a registered flight-recorder event.
4. **Registry drift** — a declared span/metric/event that no linted code
   references as a string literal (outside the registry's own declaration
   lists) is dead telemetry or a missed migration.
5. **Docs are generated** — the three table blocks in
   ``doc/observability.md`` must equal the registry's rendered output
   (``python -m raydp_tpu.metrics --write-docs`` regenerates).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from raydp_tpu.tools.rdtlint.core import (
    Project, SourceFile, Violation, marker_block_violation)
from raydp_tpu.tools.rdtlint.rule_knobs import _load_registry

RULE = "telemetry-registry"

_METRIC_FUNCS = {"inc": "counter", "set_gauge": "gauge",
                 "observe": "histogram"}
_SPAN_FUNCS = ("trace", "open_span")
_REGEN = "python -m raydp_tpu.metrics --write-docs"


def _find_registry(project: Project) -> Optional[SourceFile]:
    """The telemetry registry module — identified by content, not just the
    basename (``raydp_tpu/train/metrics.py`` is the unrelated train-metric
    classes)."""
    for f in project.files:
        if f.rel.replace("\\", "/").endswith("metrics.py") \
                and "SPAN_NAMES" in f.text and "_ALL_METRICS" in f.text:
            return f
    return None


def _module_aliases(src: SourceFile, modname: str) -> Set[str]:
    """Local names bound to ``raydp_tpu.<modname>`` in this file — the
    package-qualified twin of rule_knobs' alias scan, narrowed so
    ``from raydp_tpu.train import metrics`` (a different module) never
    aliases the telemetry registry."""
    aliases: Set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == f"raydp_tpu.{modname}":
                    aliases.add(a.asname or "raydp_tpu")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "raydp_tpu":
                for a in node.names:
                    if a.name == modname:
                        aliases.add(a.asname or a.name)
    return aliases


def _declaration_lines(reg_src: SourceFile) -> Set[int]:
    """Line numbers of the registry's own declaration lists — string
    literals there are definitions, not references, for the drift check."""
    lines: Set[int] = set()
    for node in ast.walk(reg_src.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id in ("_ALL_METRICS", "_ALL_SPANS",
                                           "_ALL_EVENTS"):
            lines.update(range(node.lineno, (node.end_lineno or
                                             node.lineno) + 1))
    return lines


def _literal_arg0(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def check(project: Project) -> List[Violation]:
    reg_src = _find_registry(project)
    if reg_src is None:
        return []  # registry out of scope: nothing to check against
    out: List[Violation] = []
    try:
        mod = _load_registry(reg_src.path)
        span_names = set(mod.SPAN_NAMES)
        span_prefixes = tuple(mod.SPAN_PREFIXES)
        metrics_reg = mod.METRICS
        events_reg = mod.EVENTS
    except Exception as e:  # noqa: BLE001 - a broken registry IS a finding
        return [Violation(rule=RULE, path=reg_src.rel, line=1,
                          message=f"could not load telemetry registry: "
                                  f"{e!r}")]

    decl_lines = _declaration_lines(reg_src)
    referenced: Set[str] = set()
    all_names = (span_names | set(metrics_reg) | set(events_reg)
                 | set(span_prefixes))

    for src in project.files:
        prof_aliases = _module_aliases(src, "profiler")
        met_aliases = _module_aliases(src, "metrics")
        for node in ast.walk(src.tree):
            # ---- reference scan (for the drift check) -------------------
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value in all_names:
                if src.path == reg_src.path and node.lineno in decl_lines:
                    pass  # a declaration is not a reference
                elif not isinstance(src.parent(node), ast.Expr):
                    referenced.add(node.value)
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or not isinstance(node.func.value, ast.Name):
                continue
            recv, attr = node.func.value.id, node.func.attr
            # ---- span names ---------------------------------------------
            if recv in prof_aliases and attr in _SPAN_FUNCS:
                name = _literal_arg0(node)
                if name is None:
                    continue  # f-string/variable: a declared dynamic family
                if name not in span_names \
                        and not name.startswith(span_prefixes):
                    out.append(Violation(
                        rule=RULE, path=src.rel, line=node.lineno,
                        message=(f"span {name!r} is not declared in the "
                                 "telemetry registry "
                                 "(raydp_tpu/metrics.py SPANS)")))
            # ---- metric names + kinds -----------------------------------
            elif recv in met_aliases and attr in _METRIC_FUNCS:
                name = _literal_arg0(node)
                if name is None:
                    continue
                want = _METRIC_FUNCS[attr]
                m = metrics_reg.get(name)
                if m is None:
                    out.append(Violation(
                        rule=RULE, path=src.rel, line=node.lineno,
                        message=(f"metric {name!r} is not declared in the "
                                 "telemetry registry "
                                 "(raydp_tpu/metrics.py METRICS)")))
                elif m.kind != want:
                    out.append(Violation(
                        rule=RULE, path=src.rel, line=node.lineno,
                        message=(f"metrics.{attr}({name!r}): declared as a "
                                 f"{m.kind}, but {attr}() is the {want} "
                                 "API")))
            # ---- event kinds --------------------------------------------
            elif recv in met_aliases and attr == "record_event":
                name = _literal_arg0(node)
                if name is not None and name not in events_reg:
                    out.append(Violation(
                        rule=RULE, path=src.rel, line=node.lineno,
                        message=(f"flight-recorder event {name!r} is not "
                                 "declared in the telemetry registry "
                                 "(raydp_tpu/metrics.py EVENTS)")))

    # ---- registry drift: declared but never referenced -------------------
    if any(f.path != reg_src.path for f in project.files):
        for name in sorted((span_names | set(metrics_reg)
                            | set(events_reg)) - referenced):
            out.append(Violation(
                rule=RULE, path=reg_src.rel, line=1,
                message=(f"{name!r} is declared in the telemetry registry "
                         "but no linted code references it — dead "
                         "telemetry or missed migration")))

    # ---- generated doc tables --------------------------------------------
    import os
    if os.path.isdir(os.path.join(project.root, "doc")):
        path = os.path.join(project.root, mod.DOC_FILE)
        if not os.path.exists(path):
            out.append(Violation(
                rule=RULE, path=mod.DOC_FILE, line=1,
                message="telemetry-table doc file missing"))
        else:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
            for tag in mod.DOC_TAGS:
                begin, end = mod.table_markers(tag)
                v = marker_block_violation(
                    RULE, mod.DOC_FILE, text, begin, end,
                    mod.render_block(tag), f"telemetry {tag}", _REGEN)
                if v is not None:
                    out.append(v)
    return out
