"""The static RPC surface map: which remote methods exist, with what
signatures, on which server class.

Everything control-plane in this repo is stringly-typed glue — every hop is
``client.call("method_name", args...)`` resolved by ``getattr`` at run time
(``runtime/rpc.py`` MethodDispatcher), so a typo'd name or drifted arity is
a runtime ``AttributeError``/``TypeError`` inside a ``RemoteError``, found
only when that exact hop fires. This module rebuilds the surface from the
AST so rule ``rpc-surface`` (and the generated table in ``doc/dev_lint.md``)
can check call sites against it:

- public methods of the configured dispatch-target classes
  (:data:`config.RPC_SURFACE_CLASSES`) plus any class auto-detected as a
  ``MethodDispatcher(Cls(...))`` / ``RpcServer(Cls(...))`` target;
- ``__call__(self, method, ...)`` if-chain handlers (``_WorkerService``,
  ``_ActorServer``): their ``method == "literal"`` branches become surface
  entries, with the arity of the helper the branch forwards ``*args`` to;
- the head's ``store_<m>`` proxies, resolved through to the
  ``ObjectStoreServer.<m>`` signature they forward to.

Pure AST — no raydp_tpu runtime import.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from raydp_tpu.tools.rdtlint import config
from raydp_tpu.tools.rdtlint.core import Project, SourceFile


@dataclass
class MethodSig:
    """One remote method's call contract, extracted from its ``def``."""

    name: str
    cls: str
    rel: str
    line: int
    pos_names: Tuple[str, ...] = ()     # positional params, self stripped
    min_pos: int = 0
    max_pos: Optional[int] = None       # None = *args
    kwnames: frozenset = frozenset()
    has_kwargs: bool = False
    note: str = ""                      # e.g. "proxy → ObjectStoreServer.seal"

    def render_args(self) -> str:
        parts = list(self.pos_names[:self.min_pos])
        parts += [f"{n}=…" for n in self.pos_names[self.min_pos:]]
        if self.max_pos is None:
            parts.append("*args")
        parts += [f"{n}=…" for n in sorted(self.kwnames
                                           - set(self.pos_names))]
        if self.has_kwargs:
            parts.append("**kw")
        return ", ".join(parts)

    def check_call(self, pos_args: List[ast.AST],
                   keywords: List[ast.keyword]) -> Optional[str]:
        """None when the call site fits this signature, else a message.
        ``timeout=`` is excluded (consumed by RpcClient.call, never
        forwarded)."""
        if any(isinstance(a, ast.Starred) for a in pos_args) \
                or any(kw.arg is None for kw in keywords):
            return None  # *args / **kwargs at the call site: unknowable
        npos = len(pos_args)
        named = set()
        for kw in keywords:
            if kw.arg == "timeout":
                continue
            if kw.arg in self.kwnames or self.has_kwargs:
                named.add(kw.arg)
            else:
                return (f"unknown keyword {kw.arg!r} (remote signature: "
                        f"{self.name}({self.render_args()}))")
        if self.max_pos is not None and npos > self.max_pos:
            return (f"{npos} positional argument(s) but the remote "
                    f"signature takes at most {self.max_pos}: "
                    f"{self.name}({self.render_args()})")
        # positional params satisfied positionally or by a matching keyword
        satisfied = npos + len(named & set(self.pos_names[npos:]))
        if satisfied < self.min_pos:
            return (f"{npos} positional argument(s) but the remote "
                    f"signature requires {self.min_pos}: "
                    f"{self.name}({self.render_args()})")
        return None


@dataclass
class SurfaceMap:
    #: surface tag -> method name -> MethodSig
    surfaces: Dict[str, Dict[str, MethodSig]] = field(default_factory=dict)
    #: class name -> (SourceFile, ClassDef) for every scanned class
    class_defs: Dict[str, Tuple[SourceFile, ast.ClassDef]] = field(
        default_factory=dict)

    def methods(self, tag: str) -> Dict[str, MethodSig]:
        return self.surfaces.get(tag, {})

    def union(self) -> Dict[str, List[MethodSig]]:
        out: Dict[str, List[MethodSig]] = {}
        for tag in self.surfaces:
            for name, sig in self.surfaces[tag].items():
                out.setdefault(name, []).append(sig)
        return out

    def has_surface(self, tag: str) -> bool:
        return bool(self.surfaces.get(tag))


def sig_of(fn: ast.FunctionDef, cls: str, rel: str,
           note: str = "") -> MethodSig:
    a = fn.args
    pos = [p.arg for p in a.posonlyargs + a.args]
    if pos and pos[0] in ("self", "cls"):
        pos = pos[1:]
    n_def = len(a.defaults)
    return MethodSig(
        name=fn.name, cls=cls, rel=rel, line=fn.lineno,
        pos_names=tuple(pos),
        min_pos=max(0, len(pos) - n_def),
        max_pos=None if a.vararg else len(pos),
        kwnames=frozenset(pos) | {p.arg for p in a.kwonlyargs},
        has_kwargs=a.kwarg is not None,
        note=note)


def _if_chain_entries(src: SourceFile, cls: ast.ClassDef
                      ) -> Optional[Dict[str, MethodSig]]:
    """Surface of a ``__call__(self, method, args, kwargs)`` if-chain
    handler; None when the class has no such handler. A branch returning
    ``self._helper(*args)`` takes the helper's signature; anything else is
    arity-unconstrained."""
    call = next((n for n in cls.body if isinstance(n, ast.FunctionDef)
                 and n.name == "__call__"), None)
    if call is None:
        return None
    params = [p.arg for p in call.args.args]
    if len(params) < 2 or params[1] != "method":
        return None
    helpers = {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}
    out: Dict[str, MethodSig] = {}
    for node in ast.walk(call):
        if not isinstance(node, ast.If):
            continue
        t = node.test
        if not (isinstance(t, ast.Compare) and isinstance(t.left, ast.Name)
                and t.left.id == "method" and len(t.ops) == 1
                and isinstance(t.ops[0], ast.Eq)
                and isinstance(t.comparators[0], ast.Constant)
                and isinstance(t.comparators[0].value, str)):
            continue
        meth = t.comparators[0].value
        sig = MethodSig(name=meth, cls=cls.name, rel=src.rel,
                        line=node.lineno, note="dispatch if-chain")
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and isinstance(sub.func.value, ast.Name) \
                    and sub.func.value.id == "self" \
                    and sub.func.attr in helpers \
                    and any(isinstance(arg, ast.Starred)
                            for arg in sub.args):
                helper = sig_of(helpers[sub.func.attr], cls.name, src.rel,
                                note=f"dispatch if-chain → "
                                     f"{cls.name}.{sub.func.attr}")
                sig = MethodSig(name=meth, cls=cls.name, rel=src.rel,
                                line=node.lineno, pos_names=helper.pos_names,
                                min_pos=helper.min_pos,
                                max_pos=helper.max_pos,
                                kwnames=helper.kwnames,
                                has_kwargs=helper.has_kwargs,
                                note=sig.note or helper.note)
                break
        out[meth] = sig
    return out or None


def _detected_dispatch_classes(project: Project) -> List[str]:
    """Class names constructed directly inside ``MethodDispatcher(...)`` /
    ``RpcServer(...)`` — the same auto-detection the dispatcher-blocking
    rule uses, so fixtures need no config edits."""
    out: List[str] = []
    for src in project.files:
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in ("MethodDispatcher", "RpcServer")
                    and node.args):
                continue
            inner = node.args[0]
            if isinstance(inner, ast.Call) \
                    and isinstance(inner.func, ast.Name):
                if inner.func.id == "MethodDispatcher" and inner.args \
                        and isinstance(inner.args[0], ast.Call) \
                        and isinstance(inner.args[0].func, ast.Name):
                    inner = inner.args[0]
                if inner.func.id != "MethodDispatcher":
                    out.append(inner.func.id)
    return out


def build(project: Project) -> SurfaceMap:
    smap = SurfaceMap()
    for src in project.files:
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                smap.class_defs.setdefault(node.name, (src, node))

    by_class: Dict[str, str] = {}
    for tag, classes in config.RPC_SURFACE_CLASSES.items():
        for cls in classes:
            by_class[cls] = tag
    for cls in _detected_dispatch_classes(project):
        by_class.setdefault(cls, f"detected:{cls}")

    for cls, tag in sorted(by_class.items()):
        found = smap.class_defs.get(cls)
        if found is None:
            continue
        src, node = found
        methods = smap.surfaces.setdefault(tag, {})
        chain = _if_chain_entries(src, node)
        if chain:
            methods.update(chain)
        for item in node.body:
            if isinstance(item, ast.FunctionDef) \
                    and not item.name.startswith("_"):
                methods[item.name] = sig_of(item, cls, src.rel)

    # resolve head store_* proxies through to the store server's signature:
    # `def store_seal(self, *a)` carries no arity of its own
    head = smap.surfaces.get("head", {})
    store = smap.surfaces.get("store", {})
    prefix = config.RPC_STORE_PROXY_PREFIX
    for name in list(head):
        if not name.startswith(prefix):
            continue
        target = store.get(name[len(prefix):])
        proxy = head[name]
        if target is not None and proxy.max_pos is None \
                and not proxy.pos_names:
            head[name] = MethodSig(
                name=name, cls=proxy.cls, rel=proxy.rel, line=proxy.line,
                pos_names=target.pos_names, min_pos=target.min_pos,
                max_pos=target.max_pos, kwnames=target.kwnames,
                has_kwargs=target.has_kwargs,
                note=f"proxy → {target.cls}.{target.name}")
    return smap


# ---- generated doc table -----------------------------------------------------

RPC_TABLE_BEGIN = "<!-- rdtlint:rpc-table:begin -->"
RPC_TABLE_END = "<!-- rdtlint:rpc-table:end -->"

#: tag → how the table labels the surface (detected:* tags are fixture-only
#: and never reach the doc)
_TABLE_SURFACES = (
    ("head", "head (`HeadService`)"),
    ("agent", "node agent (`NodeAgentService`)"),
    ("store", "store table (`ObjectStoreServer`)"),
    ("driver", "SPMD driver (`_DriverService`)"),
    ("worker", "SPMD worker (`_WorkerService`)"),
    ("actor", "actor dispatch"),
)


def generate_table(smap: SurfaceMap) -> str:
    lines = ["| Surface | Method | Arguments | Notes |",
             "| --- | --- | --- | --- |"]
    for tag, label in _TABLE_SURFACES:
        for name in sorted(smap.methods(tag)):
            sig = smap.methods(tag)[name]
            args = sig.render_args() or "—"
            note = sig.note
            if tag == "actor" and sig.cls != "_ActorServer":
                note = (note + "; " if note else "") + f"`{sig.cls}`"
            lines.append(f"| {label} | `{name}` | `{args}` | {note} |")
    return "\n".join(lines)


def render_block(smap: SurfaceMap) -> str:
    return f"{RPC_TABLE_BEGIN}\n{generate_table(smap)}\n{RPC_TABLE_END}"


def write_doc_table(project: Project, doc_rel: str = "doc/dev_lint.md"
                    ) -> List[str]:
    """Rewrite the marker block from the current surface map; returns the
    files changed (empty = already fresh). Used by ``--write-rpc-docs``.

    Fails LOUDLY when the doc or its markers are missing — a wrong ``--root``
    must not report success while the drift fence keeps failing (the same
    contract as core.Project.load's missing-path error)."""
    import os

    path = os.path.join(project.root, doc_rel)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no {doc_rel} under {project.root} — wrong --root?")
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    if RPC_TABLE_BEGIN not in text or RPC_TABLE_END not in text:
        raise ValueError(
            f"{doc_rel} has no {RPC_TABLE_BEGIN} / {RPC_TABLE_END} markers "
            "— add them where the table should live, then rerun")
    head_part, rest = text.split(RPC_TABLE_BEGIN, 1)
    _, tail = rest.split(RPC_TABLE_END, 1)
    new = head_part + render_block(build(project)) + tail
    if new == text:
        return []
    with open(path, "w", encoding="utf-8") as f:
        f.write(new)
    return [doc_rel]
