"""raydp_tpu.train — the JAX/XLA training tier (L5 Estimator parity).

The reference's L5 is three sklearn-style estimators over Ray Train
(torch/estimator.py, tf/estimator.py, xgboost/estimator.py) sharing the shape
``fit`` / ``fit_on_spark`` / ``get_model`` (estimator.py:23-43,
spark/interfaces.py:27-39). Here the training engine is pjit-compiled SPMD over a
device mesh: the DDP wrap + per-step torch.distributed allreduce
(torch/estimator.py:243,272-293) become sharding annotations — XLA emits the
gradient ``psum`` over ICI.
"""

from raydp_tpu.train.estimator import EstimatorInterface, FrameEstimatorInterface
from raydp_tpu.train.flax_estimator import (FlaxEstimator, PipelineModel,
                                            TrainingResult)
from raydp_tpu.train.metrics import Metric, build_metrics

from raydp_tpu.train.gbdt_estimator import GBDTEstimator

__all__ = [
    "EstimatorInterface",
    "FrameEstimatorInterface",
    "FlaxEstimator",
    "GBDTEstimator",
    "PipelineModel",
    "KerasEstimator",
    "TrainingResult",
    "Metric",
    "build_metrics",
]


def __getattr__(name):
    # keras imports TF-adjacent machinery at module load; keep it lazy so the
    # core train tier stays import-light
    if name == "KerasEstimator":
        from raydp_tpu.train.keras_estimator import KerasEstimator
        return KerasEstimator
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
