"""Checkpoint save/restore via orbax (parity: Ray Train Checkpoint usage,
torch/estimator.py:259-270, 392-396 — rank-0 writes, ``get_model`` rehydrates).

Only process 0 writes (chief-only, tf/estimator.py:202-210). Checkpoints are
``step_<n>`` subdirectories; ``restore`` picks the latest complete one. Unlike the
reference (no mid-training resume, SURVEY.md §5), a restored state resumes the
epoch loop where it left off.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Optional, Tuple

from raydp_tpu.log import get_logger

logger = get_logger("train.checkpoint")

_KEEP = 2


def _step_dirs(ckpt_dir: str):
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        if name.startswith("step_"):
            try:
                out.append((int(name.split("_", 1)[1]), os.path.join(ckpt_dir, name)))
            except ValueError:
                pass
    return sorted(out)


def _checkpointer():
    """An orbax checkpointer whose barriers never leave this process.

    Under a multi-process gang only the chief saves (and every rank restores
    independently from shared storage); stock orbax would run a
    ``sync_global_devices`` barrier across ALL processes inside save() —
    called from one rank, that deadlocks the gang (observed as a Gloo clique
    of one device per process timing out). ``active_processes={self}`` scopes
    every barrier to the calling process.
    """
    import jax
    import orbax.checkpoint as ocp

    if jax.process_count() > 1:
        from orbax.checkpoint.options import MultiprocessingOptions
        me = jax.process_index()
        return ocp.Checkpointer(
            ocp.PyTreeCheckpointHandler(),
            multiprocessing_options=MultiprocessingOptions(
                primary_host=me, active_processes={me},
                barrier_sync_key_prefix=f"proc{me}"))
    return ocp.PyTreeCheckpointer()


def save(ckpt_dir: str, state: Any, step: int,
         extra: Optional[dict] = None) -> Optional[str]:
    """Chief-only checkpoint write. ``extra`` is a JSON-serializable sidecar
    (e.g. the accumulated epoch history, so a restarted gang's result is not
    truncated to post-restart epochs)."""
    import jax

    if jax.process_index() != 0:
        return None

    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    if os.path.exists(path):
        shutil.rmtree(path)
    with _checkpointer() as ckptr:
        ckptr.save(path, jax.device_get(state))
    if extra is not None:
        import json
        tmp = os.path.join(ckpt_dir, f".extra_{step}.tmp")
        with open(tmp, "w") as f:
            json.dump(extra, f)
        os.replace(tmp, os.path.join(path, "extra.json"))
    # retention: keep the newest _KEEP
    steps = _step_dirs(ckpt_dir)
    for _, old in steps[:-_KEEP]:
        shutil.rmtree(old, ignore_errors=True)
    return path


def restore(ckpt_dir: str, template: Any) -> Optional[Tuple[Any, int]]:
    """Restore the latest checkpoint into the structure of ``template``.

    Returns ``(state, step)`` or None if no checkpoint exists.
    """
    import jax

    steps = _step_dirs(ckpt_dir)
    if not steps:
        return None
    step, path = steps[-1]
    with _checkpointer() as ckptr:
        restored = ckptr.restore(path, item=jax.device_get(template))
    return restored, step


def restore_extra(ckpt_dir: str) -> Optional[dict]:
    """The JSON sidecar of the latest checkpoint, or None."""
    import json

    steps = _step_dirs(ckpt_dir)
    if not steps:
        return None
    path = os.path.join(steps[-1][1], "extra.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)
