"""Checkpoint save/restore (parity: Ray Train Checkpoint usage,
torch/estimator.py:259-270, 392-396 — rank-0 writes, ``get_model`` rehydrates).

Two on-disk formats, selected by the process topology:

- **single process** — orbax ``PyTreeCheckpointer`` (chief-only,
  tf/estimator.py:202-210).
- **multi-process gang** — a *sharded* format: every process writes exactly the
  array shards it owns (``replica_id == 0`` filtering makes each unique index
  land once across the gang) as ``shard_<p>.npz`` + ``manifest_<p>.json``,
  with cross-process ``sync_global_devices`` barriers around the write and a
  chief-written ``COMPLETE`` marker for atomicity. This is what lets a gang
  train with parameters sharded *across* processes (fsdp/expert axes spanning
  hosts): no process ever needs to materialize the full state.

Checkpoints are ``step_<n>`` subdirectories; ``restore``/``restore_placed``
pick the latest complete one. Either format can be read back by either
topology (a driver process can reassemble a gang's sharded checkpoint).
Unlike the reference (no mid-training resume, SURVEY.md §5), a restored state
resumes the epoch loop where it left off.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
from typing import Any, Optional, Tuple

import numpy as np

from raydp_tpu.log import get_logger

logger = get_logger("train.checkpoint")

_KEEP = 2


def _is_complete(path: str) -> bool:
    """Sharded-format dirs need the chief's COMPLETE marker; orbax dirs count
    when orbax's own metadata landed. Anything else (e.g. a directory a gang
    created moments before a rank died, never written) is torn — restore must
    skip it and fall back to the previous step."""
    if os.path.exists(os.path.join(path, "COMPLETE")):
        return True
    if glob.glob(os.path.join(path, "manifest_*.json")):
        return False  # sharded write without the chief marker = torn
    # _METADATA / _CHECKPOINT_METADATA: current orbax; bare "checkpoint"
    # msgpack: older orbax aggregate format (pre-existing checkpoints must
    # not read as torn, or resume silently restarts from scratch)
    return os.path.exists(os.path.join(path, "_METADATA")) \
        or os.path.exists(os.path.join(path, "_CHECKPOINT_METADATA")) \
        or os.path.exists(os.path.join(path, "checkpoint"))


def _step_dirs(ckpt_dir: str, complete_only: bool = True):
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        if name.startswith("step_"):
            try:
                path = os.path.join(ckpt_dir, name)
                if not complete_only or _is_complete(path):
                    out.append((int(name.split("_", 1)[1]), path))
            except ValueError:
                pass
    return sorted(out)


def _latest_agreed(ckpt_dir: str, max_step: Optional[int] = None
                   ) -> Optional[Tuple[int, str]]:
    """The ``(step, path)`` every rank will restore.

    Single process: the locally-latest complete step. Multi-process gang:
    ranks can disagree on which step is complete (lagging COMPLETE/manifest
    visibility on networked storage), and ranks resuming different epochs
    deadlock the first collective — so every rank takes the CHIEF's choice
    (broadcast), and a rank that cannot see that step fails fast with a
    shared-storage message instead of silently training from elsewhere.

    ``max_step`` bounds the choice: a fresh fit's retry passes the highest
    step it wrote itself, so stale higher-step dirs left in a reused
    checkpoint_dir by an earlier run are never adopted."""
    steps = _step_dirs(ckpt_dir)
    if max_step is not None:
        steps = [s for s in steps if s[0] <= max_step]
    import jax
    if jax.process_count() <= 1:
        return steps[-1] if steps else None
    from jax.experimental import multihost_utils
    local = steps[-1][0] if steps else -1
    chief = int(multihost_utils.broadcast_one_to_all(np.int32(local)))
    if chief < 0:
        return None
    for step, path in steps:
        if step == chief:
            return step, path
    raise FileNotFoundError(
        f"chief rank restores checkpoint step {chief} but rank "
        f"{jax.process_index()} only sees steps {[s for s, _ in steps]} in "
        f"{ckpt_dir!r}; multi-process gangs require checkpoint_dir on "
        "shared storage visible to every rank")


def warn_if_reused_dir(ckpt_dir: str) -> None:
    """A fresh (non-resume) fit pointed at a dir that already holds ``step_*``
    checkpoints: retention and retry-restore are scoped to THIS run's steps
    (``_latest_agreed(max_step=...)``), but a later explicit resume or
    ``restore()`` without ``max_step`` would silently prefer the foreign
    higher-numbered steps — tell the user the dir is reused up front."""
    steps = _step_dirs(ckpt_dir, complete_only=False)
    if steps:
        logger.warning(
            "checkpoint_dir %r already contains %d step_* checkpoint dir(s) "
            "(latest: step_%d) from an earlier run; this fit will not adopt "
            "them, but a later resume/restore() on this dir would — use a "
            "fresh checkpoint_dir per run to keep runs separate",
            ckpt_dir, len(steps), steps[-1][0])


def ensure_shared_dir(ckpt_dir: str, tag: str) -> None:
    """Gang-startup probe: the chief creates ``ckpt_dir``; every other rank
    must see it after a barrier, else the gang runs on per-host paths and a
    later save/resume deadlocks collectives. Fail fast with a shared-storage
    message instead. No-op single-process."""
    import jax
    if jax.process_count() <= 1:
        os.makedirs(ckpt_dir, exist_ok=True)
        return
    from jax.experimental import multihost_utils
    if jax.process_index() == 0:
        os.makedirs(ckpt_dir, exist_ok=True)
    multihost_utils.sync_global_devices(tag)
    if not os.path.isdir(ckpt_dir):
        raise RuntimeError(
            f"checkpoint_dir {ckpt_dir!r} is not visible on rank "
            f"{jax.process_index()}'s machine: multi-process gangs need "
            "shared storage for checkpoints — pass a checkpoint_dir on a "
            "filesystem mounted on every rank's host")


def _checkpointer():
    """An orbax checkpointer whose barriers never leave this process.

    Under a multi-process gang only the chief saves (and every rank restores
    independently from shared storage); stock orbax would run a
    ``sync_global_devices`` barrier across ALL processes inside save() —
    called from one rank, that deadlocks the gang (observed as a Gloo clique
    of one device per process timing out). ``active_processes={self}`` scopes
    every barrier to the calling process.
    """
    import jax
    import orbax.checkpoint as ocp

    if jax.process_count() > 1:
        from orbax.checkpoint.options import MultiprocessingOptions
        me = jax.process_index()
        return ocp.Checkpointer(
            ocp.PyTreeCheckpointHandler(),
            multiprocessing_options=MultiprocessingOptions(
                primary_host=me, active_processes={me},
                barrier_sync_key_prefix=f"proc{me}"))
    return ocp.PyTreeCheckpointer()


def _write_extra(path: str, ckpt_dir: str, step: int, extra: dict) -> None:
    tmp = os.path.join(ckpt_dir, f".extra_{step}.tmp")
    with open(tmp, "w") as f:
        json.dump(extra, f)
    os.replace(tmp, os.path.join(path, "extra.json"))


def _index_to_json(index, shape):
    out = []
    for i, sl in enumerate(index):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(shape[i]) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _flatten_with_keys(tree):
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(kp), leaf) for kp, leaf in flat], treedef


def _raw(arr: np.ndarray) -> np.ndarray:
    """Flat uint8 view of an array's bytes. ``np.savez`` silently stores
    extension dtypes (ml_dtypes bfloat16 etc.) as raw void and cannot load
    them back, so every entry is stored as bytes and re-viewed through the
    manifest's dtype on load."""
    return np.frombuffer(np.ascontiguousarray(arr).tobytes(), np.uint8)


def _entry_array(npz, e: dict) -> np.ndarray:
    data = npz[e["arr"]]
    return data.view(np.dtype(e["dtype"])).reshape(
        [t - s for s, t in e["index"]])


def _save_sharded(ckpt_dir: str, state: Any, step: int,
                  extra: Optional[dict]) -> str:
    """Every gang process writes its owned shards; barriers make the write a
    gang-wide atomic step (COMPLETE marker last, chief-only)."""
    import jax
    from jax.experimental import multihost_utils

    me = jax.process_index()
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    if me == 0:
        os.makedirs(ckpt_dir, exist_ok=True)
        if os.path.exists(path):
            shutil.rmtree(path)
        os.makedirs(path)
    multihost_utils.sync_global_devices(f"rdt_ckpt_mk_{step}")

    flat, _ = _flatten_with_keys(state)
    arrays, manifest = {}, []
    n = 0
    for key, leaf in flat:
        is_global = (isinstance(leaf, jax.Array)
                     and hasattr(leaf, "addressable_shards")
                     and not leaf.is_fully_addressable)
        if is_global:
            # replica_id == 0 appears on exactly one device GANG-WIDE for a
            # global array, so each unique index lands once across processes
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                name = f"a{n}"
                n += 1
                arrays[name] = _raw(np.asarray(shard.data))
                manifest.append({
                    "key": key, "arr": name,
                    "index": _index_to_json(shard.index, leaf.shape),
                    "shape": list(leaf.shape), "dtype": str(leaf.dtype)})
        elif me == 0:
            # process-local leaf (host scalar / numpy / fully-addressable
            # array): every process holds its own full copy with replica_id 0,
            # so the shard filter would dedup NOTHING — chief's value wins,
            # written once (orbax chief-only semantics for local state)
            arr = np.asarray(leaf)
            name = f"a{n}"
            n += 1
            arrays[name] = _raw(arr)
            manifest.append({"key": key, "arr": name,
                             "index": [[0, s] for s in arr.shape],
                             "shape": list(arr.shape),
                             "dtype": str(arr.dtype)})
    np.savez(os.path.join(path, f"shard_{me}.npz"), **arrays)
    with open(os.path.join(path, f"manifest_{me}.json"), "w") as f:
        json.dump(manifest, f)
    multihost_utils.sync_global_devices(f"rdt_ckpt_done_{step}")
    if me == 0:
        if extra is not None:
            _write_extra(path, ckpt_dir, step, extra)
        open(os.path.join(path, "COMPLETE"), "w").close()
        _prune(ckpt_dir, step)
    return path


def _prune(ckpt_dir: str, written_step: int) -> None:
    """Retention: keep the newest ``_KEEP`` steps AT OR BELOW the one just
    written. Bounding at ``written_step`` means stale higher-step dirs in a
    reused directory are left alone (they are foreign data, and pruning
    lower steps in their favor would delete the checkpoint written
    milliseconds earlier while keeping another run's)."""
    steps = [s for s in _step_dirs(ckpt_dir, complete_only=False)
             if s[0] <= written_step]
    for _, old in steps[:-_KEEP]:
        shutil.rmtree(old, ignore_errors=True)


def save(ckpt_dir: str, state: Any, step: int,
         extra: Optional[dict] = None) -> Optional[str]:
    """Checkpoint write. Single-process: chief-only orbax. Gang: every process
    writes its shards (call from ALL ranks — it contains barriers). ``extra``
    is a JSON-serializable sidecar (e.g. the accumulated epoch history, so a
    restarted gang's result is not truncated to post-restart epochs)."""
    import jax

    if jax.process_count() > 1:
        return _save_sharded(ckpt_dir, state, step, extra)

    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    if os.path.exists(path):
        shutil.rmtree(path)
    with _checkpointer() as ckptr:
        ckptr.save(path, jax.device_get(state))
    if extra is not None:
        _write_extra(path, ckpt_dir, step, extra)
    _prune(ckpt_dir, step)
    return path


def _load_manifests(path: str) -> dict:
    """key → list of (entry, shard_file) across every process's manifest."""
    entries: dict = {}
    for mf in sorted(glob.glob(os.path.join(path, "manifest_*.json"))):
        shard_file = mf.replace("manifest_", "shard_")[:-len(".json")] + ".npz"
        with open(mf) as f:
            for e in json.load(f):
                entries.setdefault(e["key"], []).append((e, shard_file))
    return entries


class _NpzCache:
    """Open-once NpzFile cache; close() after assembly (retry loops restore
    repeatedly — leaked zip handles would accumulate fds for the process
    lifetime)."""

    def __init__(self):
        self._files: dict = {}

    def __call__(self, fpath: str):
        npz = self._files.get(fpath)
        if npz is None:
            npz = self._files[fpath] = np.load(fpath)
        return npz

    def close(self) -> None:
        for npz in self._files.values():
            try:
                npz.close()
            except Exception:
                pass
        self._files.clear()


def _assemble_full(recs, files: "_NpzCache") -> np.ndarray:
    e0 = recs[0][0]
    full = np.empty(tuple(e0["shape"]), dtype=np.dtype(e0["dtype"]))
    for e, fpath in recs:
        full[tuple(slice(s, t) for s, t in e["index"])] = \
            _entry_array(files(fpath), e)
    return full


def _restore_sharded_host(path: str, template: Any) -> Any:
    """Reassemble full host arrays (any process count) from a sharded-format
    checkpoint into the structure of ``template``."""
    import jax

    entries = _load_manifests(path)
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    files = _NpzCache()
    try:
        out = []
        for kp, _ in flat:
            key = jax.tree_util.keystr(kp)
            recs = entries.get(key)
            if not recs:
                raise KeyError(f"checkpoint at {path} is missing leaf {key}")
            out.append(_assemble_full(recs, files))
    finally:
        files.close()
    return jax.tree_util.tree_unflatten(treedef, out)


def _restore_sharded_placed(path: str, template: Any, shardings: Any) -> Any:
    """Place a sharded-format checkpoint directly under ``shardings`` reading
    only the shards THIS process addresses (exact index match — the
    unchanged-topology resume case). A leaf whose saved indices do not line up
    with the requested sharding falls back to full assembly for that leaf, so
    resharded restores still work; the common gang restart never materializes
    the full state."""
    import jax

    entries = _load_manifests(path)
    flat_t, treedef = jax.tree_util.tree_flatten_with_path(template)
    flat_s = treedef.flatten_up_to(shardings)
    files = _NpzCache()
    try:
        out = []
        for (kp, _), sharding in zip(flat_t, flat_s):
            key = jax.tree_util.keystr(kp)
            recs = entries.get(key)
            if not recs:
                raise KeyError(f"checkpoint at {path} is missing leaf {key}")
            e0 = recs[0][0]
            shape = tuple(e0["shape"])
            by_index = {tuple(tuple(se) for se in e["index"]): (e, f)
                        for e, f in recs}
            fallback: list = []  # assembled lazily, shared by the callbacks

            def cb(idx, by_index=by_index, recs=recs, shape=shape,
                   fallback=fallback):
                norm = tuple(
                    (0 if sl.start is None else int(sl.start),
                     shape[i] if sl.stop is None else int(sl.stop))
                    for i, sl in enumerate(idx))
                hit = by_index.get(norm)
                if hit is not None:
                    return _entry_array(files(hit[1]), hit[0])
                if not fallback:
                    fallback.append(_assemble_full(recs, files))
                return fallback[0][tuple(slice(s, t) for s, t in norm)]

            # make_array_from_callback runs the callbacks eagerly, so the
            # npz handles are drained before the finally closes them
            out.append(jax.make_array_from_callback(shape, sharding, cb))
    finally:
        files.close()
    return jax.tree_util.tree_unflatten(treedef, out)


def _host_template(template: Any) -> Any:
    """A host-side zeros tree with the template's shapes/dtypes — safe to build
    even when the template's leaves are cross-process global arrays (which
    ``device_get`` would reject)."""
    import jax

    return jax.tree.map(
        lambda x: np.zeros(getattr(x, "shape", ()),
                           getattr(x, "dtype", np.float32))
        if hasattr(x, "shape") else x, template)


def restore(ckpt_dir: str, template: Any) -> Optional[Tuple[Any, int]]:
    """Restore the latest checkpoint as HOST arrays into the structure of
    ``template``. Reads either format. Returns ``(state, step)`` or None.
    """
    latest = _latest_agreed(ckpt_dir)
    if latest is None:
        return None
    step, path = latest
    if glob.glob(os.path.join(path, "manifest_*.json")):
        return _restore_sharded_host(path, template), step
    with _checkpointer() as ckptr:
        restored = ckptr.restore(path, item=_host_template(template))
    return restored, step


def place_tree(tree: Any, shardings: Any) -> Any:
    """Place a host pytree under global shardings.

    Single-process: plain sharded ``device_put``. Multi-process gang:
    ``make_array_from_callback`` — every process holds the full host value
    (same rng / same restored checkpoint), each device reads its shard.
    """
    import jax

    if jax.process_count() > 1:
        def _put(x, s):
            if x is None:
                return None
            host = np.asarray(x)
            return jax.make_array_from_callback(
                host.shape, s, lambda idx: host[idx])
    else:
        def _put(x, s):
            return None if x is None else jax.device_put(x, s)
    return jax.tree.map(_put, tree, shardings, is_leaf=lambda x: x is None)


def restore_placed(ckpt_dir: str, template: Any, shardings: Any,
                   max_step: Optional[int] = None
                   ) -> Optional[Tuple[Any, int]]:
    """Restore the latest checkpoint and place it under ``shardings`` —
    correct in both single-process and gang topologies, for both formats.
    Sharded-format checkpoints restore shard-locally (each process reads only
    what its devices address). Returns ``(placed_state, step)`` or None.
    ``max_step`` restricts to steps the caller knows are its own (see
    :func:`_latest_agreed`)."""
    latest = _latest_agreed(ckpt_dir, max_step=max_step)
    if latest is None:
        return None
    step, path = latest
    if glob.glob(os.path.join(path, "manifest_*.json")):
        return _restore_sharded_placed(path, template, shardings), step
    with _checkpointer() as ckptr:
        host_state = ckptr.restore(path, item=_host_template(template))
    return place_tree(host_state, shardings), step


def restore_extra(ckpt_dir: str, max_step: Optional[int] = None
                  ) -> Optional[dict]:
    """The JSON sidecar of the latest checkpoint, or None. Gang-agreed like
    the state restore: divergent epoch bookkeeping would desynchronize the
    ranks' collective counts."""
    import json

    latest = _latest_agreed(ckpt_dir, max_step=max_step)
    if latest is None:
        return None
    path = os.path.join(latest[1], "extra.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)
