"""Checkpoint save/restore via orbax (parity: Ray Train Checkpoint usage,
torch/estimator.py:259-270, 392-396 — rank-0 writes, ``get_model`` rehydrates).

Only process 0 writes (chief-only, tf/estimator.py:202-210). Checkpoints are
``step_<n>`` subdirectories; ``restore`` picks the latest complete one. Unlike the
reference (no mid-training resume, SURVEY.md §5), a restored state resumes the
epoch loop where it left off.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Optional, Tuple

from raydp_tpu.log import get_logger

logger = get_logger("train.checkpoint")

_KEEP = 2


def _step_dirs(ckpt_dir: str):
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        if name.startswith("step_"):
            try:
                out.append((int(name.split("_", 1)[1]), os.path.join(ckpt_dir, name)))
            except ValueError:
                pass
    return sorted(out)


def save(ckpt_dir: str, state: Any, step: int) -> Optional[str]:
    import jax

    if jax.process_index() != 0:
        return None
    import orbax.checkpoint as ocp

    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    if os.path.exists(path):
        shutil.rmtree(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, jax.device_get(state))
    # retention: keep the newest _KEEP
    steps = _step_dirs(ckpt_dir)
    for _, old in steps[:-_KEEP]:
        shutil.rmtree(old, ignore_errors=True)
    return path


def restore(ckpt_dir: str, template: Any) -> Optional[Tuple[Any, int]]:
    """Restore the latest checkpoint into the structure of ``template``.

    Returns ``(state, step)`` or None if no checkpoint exists.
    """
    import jax
    import orbax.checkpoint as ocp

    steps = _step_dirs(ckpt_dir)
    if not steps:
        return None
    step, path = steps[-1]
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path, item=jax.device_get(template))
    return restored, step
