"""Estimator ABCs (parity: reference estimator.py:23-43 + spark/interfaces.py:27-39)."""

from __future__ import annotations

import os
from abc import ABC, abstractmethod
from typing import Optional


class EstimatorInterface(ABC):
    """``fit`` over datasets + ``get_model`` (reference estimator.py:23-43)."""

    @abstractmethod
    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0):
        ...

    @abstractmethod
    def get_model(self):
        ...

    def export_serving(self, export_dir: str) -> str:
        """Write a self-contained serving bundle (weights through
        ``train/checkpoint.py`` + the pickled inference recipe) that
        :class:`raydp_tpu.serve.ServingSession` loads onto executor
        replicas. Implemented by the flax and keras estimators; others
        (e.g. GBDT) have no jit-servable forward pass yet."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support export_serving()")


class FrameEstimatorInterface(ABC):
    """``fit_on_frame`` — the ``fit_on_spark`` analogue
    (spark/interfaces.py:27-39): accepts ETL DataFrames, converts through the
    data plane (object store or a parquet spill directory), optionally stops the
    ETL engine after conversion with ownership transferred to the master."""

    @abstractmethod
    def fit_on_frame(self, train_df, evaluate_df=None, *,
                     fs_directory: Optional[str] = None,
                     stop_etl_after_conversion: bool = False,
                     max_retries: int = 0):
        ...

    @staticmethod
    def _convert_frames(train_df, evaluate_df=None, *,
                        fs_directory: Optional[str] = None,
                        stop_etl_after_conversion: bool = False):
        """Frames → datasets through the chosen conversion path; optionally
        stop the ETL engine with ownership transferred to the master so the
        data survives (parity: torch/estimator.py:358-390, dataset.py:137-158).
        Shared by every concrete estimator's ``fit_on_frame``."""
        import raydp_tpu
        from raydp_tpu.data import from_frame, from_frame_recoverable

        def convert(df, tag):
            if df is None:
                return None
            if fs_directory is not None:
                # parquet spill path (parity: torch/estimator.py:365-376)
                path = os.path.join(fs_directory, tag)
                df.write.parquet(path)
                session = df._session
                return from_frame(session.read.parquet(path))
            return from_frame_recoverable(df)

        train_ds = convert(train_df, "train")
        eval_ds = convert(evaluate_df, "eval")
        if stop_etl_after_conversion:
            train_ds.transfer_to_master()
            if eval_ds is not None:
                eval_ds.transfer_to_master()
            raydp_tpu.stop(cleanup_data=False)
        return train_ds, eval_ds


def save_epoch_now(epoch: int, interval: int, num_epochs: int) -> bool:
    """The checkpoint cadence every estimator loop shares: every
    ``interval``-th epoch, and always the final one (so resume/get_model
    semantics hold at any interval)."""
    return (epoch + 1) % interval == 0 or epoch == num_epochs - 1
