"""Estimator ABCs (parity: reference estimator.py:23-43 + spark/interfaces.py:27-39)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional


class EstimatorInterface(ABC):
    """``fit`` over datasets + ``get_model`` (reference estimator.py:23-43)."""

    @abstractmethod
    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0):
        ...

    @abstractmethod
    def get_model(self):
        ...


class FrameEstimatorInterface(ABC):
    """``fit_on_frame`` — the ``fit_on_spark`` analogue
    (spark/interfaces.py:27-39): accepts ETL DataFrames, converts through the
    data plane (object store or a parquet spill directory), optionally stops the
    ETL engine after conversion with ownership transferred to the master."""

    @abstractmethod
    def fit_on_frame(self, train_df, evaluate_df=None, *,
                     fs_directory: Optional[str] = None,
                     stop_etl_after_conversion: bool = False,
                     max_retries: int = 0):
        ...
