"""Estimator ABCs (parity: reference estimator.py:23-43 + spark/interfaces.py:27-39)."""

from __future__ import annotations

import os
import tempfile
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class OnlineTrainingResult:
    """What one :meth:`EstimatorInterface.partial_fit` drive produced: the
    per-epoch train metric reports, the serving bundles it exported on the
    way (``(source epoch id, export dir)``), and how many stream epochs it
    consumed. The trained model itself lives on the estimator
    (``get_model`` / ``export_serving``), exactly as after ``fit``."""

    history: List[Dict[str, float]] = field(default_factory=list)
    exports: List[Tuple[int, str]] = field(default_factory=list)
    epochs: int = 0
    #: guarded-rollout outcome records, one per export shipped through
    #: ``rollout=`` (empty when exports hot-swap unguarded); a
    #: ``rolled_back`` entry means that epoch's model never took traffic —
    #: training continued past it by design
    rollouts: List[Dict] = field(default_factory=list)

    @property
    def final_metrics(self) -> Dict[str, float]:
        return self.history[-1] if self.history else {}


class EstimatorInterface(ABC):
    """``fit`` over datasets + ``get_model`` (reference estimator.py:23-43)."""

    @abstractmethod
    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0):
        ...

    @abstractmethod
    def get_model(self):
        ...

    def export_serving(self, export_dir: str) -> str:
        """Write a self-contained serving bundle (weights through
        ``train/checkpoint.py`` + the pickled inference recipe) that
        :class:`raydp_tpu.serve.ServingSession` loads onto executor
        replicas. Implemented by the flax and keras estimators; others
        (e.g. GBDT) have no jit-servable forward pass yet."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support export_serving()")

    # ---------------------------------------------------------- partial_fit
    def partial_fit(self, stream, *, max_epochs: Optional[int] = None,
                    export_every: Optional[int] = None,
                    export_dir: Optional[str] = None,
                    serving=None,
                    rollout: Optional[bool] = None,
                    timeout_s: Optional[float] = None
                    ) -> OnlineTrainingResult:
        """Online training over a continuous pipeline (doc/streaming.md).

        Consumes stream epochs — each one micro-batch's transformed rows,
        sealed in the object store — and updates the model incrementally:
        parameters persist across epochs (one gradient pass per epoch here,
        vs ``fit``'s many passes over one static dataset). Every epoch's
        rows flow through the same feed/``DevicePrefetcher`` plane ``fit``
        uses, and every epoch appends a train-metrics report to the
        returned history.

        ``stream`` may be a
        :class:`~raydp_tpu.stream.pipeline.ContinuousPipeline` (driven
        inline: each ``partial_fit`` step runs one source epoch), an
        :class:`~raydp_tpu.stream.pipeline.EpochStream` (a decoupled
        ledger consumer — e.g. of a pipeline running on its background
        thread), or any iterable of ``EpochResult``.

        Every ``export_every`` epochs (default ``RDT_STREAM_EXPORT_EVERY``;
        0 disables) the current model is ``export_serving``-ed under
        ``export_dir/v<n>`` and — when ``serving`` (a live
        :class:`~raydp_tpu.serve.ServingSession`) is attached — shipped
        into it under live traffic, tagged with the source epoch id:
        either an immediate atomic :meth:`hot_swap`, or, with
        ``rollout=True`` (default ``RDT_STREAM_ROLLOUT``), a GUARDED
        rollout — canary weight, ramp, per-version health judgment,
        auto-promote or auto-rollback (doc/serving.md "Guarded
        rollouts"). A rolled-back export does NOT stop training: the
        outcome lands in ``result.rollouts`` and the next epoch trains
        on — shipping a bad epoch to 100% of traffic is the failure mode
        the guard exists for, a bad epoch itself is routine.
        Stops after ``max_epochs``, or when the stream ends.
        """
        from raydp_tpu import knobs, metrics

        if export_every is None:
            export_every = int(knobs.get("RDT_STREAM_EXPORT_EVERY"))
        if rollout is None:
            rollout = bool(knobs.get("RDT_STREAM_ROLLOUT"))
        if export_every and export_dir is None:
            export_dir = tempfile.mkdtemp(prefix="rdt-online-")
        result = OnlineTrainingResult()
        for epoch_id, ds in self._stream_epochs(stream, max_epochs,
                                                timeout_s):
            t0 = time.perf_counter()
            report = self._partial_fit_epoch(ds, epoch_id)
            report.setdefault("epoch", epoch_id)
            report.setdefault("epoch_time_s", time.perf_counter() - t0)
            metrics.observe("train_epoch_seconds", report["epoch_time_s"])
            result.history.append(report)
            result.epochs += 1
            if export_every and result.epochs % export_every == 0:
                vdir = os.path.join(export_dir,
                                    f"v{len(result.exports) + 1}")
                self.export_serving(vdir)
                result.exports.append((epoch_id, vdir))
                if serving is not None:
                    tag = f"epoch-{epoch_id}"
                    if rollout:
                        result.rollouts.append(
                            serving.rollout(vdir, tag=tag))
                    else:
                        serving.hot_swap(vdir, tag=tag)
        return result

    @staticmethod
    def _stream_epochs(stream, max_epochs: Optional[int],
                       timeout_s: Optional[float]):
        """Normalize the accepted stream shapes to ``(epoch id, dataset)``
        pairs, each dataset a store-backed view of the epoch's rows."""
        from raydp_tpu.stream.pipeline import ContinuousPipeline, EpochStream

        if isinstance(stream, ContinuousPipeline):
            for er in stream.epochs(max_epochs=max_epochs,
                                    timeout_s=timeout_s):
                yield er.epoch, er.dataset()
            return
        if isinstance(stream, EpochStream):
            done = 0
            while max_epochs is None or done < max_epochs:
                item = stream.next(timeout_s if timeout_s is not None
                                   else 30.0)
                if item is None:
                    if stream.exhausted:
                        return
                    continue
                epoch, table = item
                ds, ref = _table_dataset(table)
                try:
                    # the consumer trains through the dataset before
                    # resuming this generator; the finally also covers a
                    # training failure closing the generator mid-yield
                    yield epoch, ds
                finally:
                    _free_refs([ref])
                done += 1
            return
        it = iter(stream)
        done = 0
        while max_epochs is None or done < max_epochs:
            # check the bound BEFORE pulling: a shared iterator must not
            # have an epoch consumed and silently dropped past the cap
            er = next(it, None)
            if er is None:
                return
            yield er.epoch, er.dataset()
            done += 1

    def _partial_fit_epoch(self, ds, epoch: int) -> Dict[str, float]:
        """One incremental update over one epoch's dataset; returns the
        epoch's train-metrics report. Implemented by estimators that
        support online training (flax, keras)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support partial_fit()")


def _table_dataset(table):
    """An already-fetched epoch table as a 1-block feed-plane dataset
    (the EpochStream consumer path — its tables left the store already).
    Returns ``(dataset, ref)``; the caller frees ``ref`` after training."""
    from raydp_tpu.data.dataset import BlockMeta, DistributedDataset
    from raydp_tpu.runtime.object_store import get_client

    ref = get_client().put_arrow(table)
    return DistributedDataset([BlockMeta(num_rows=table.num_rows, ref=ref)],
                              table.schema), ref


def _free_refs(refs) -> None:
    from raydp_tpu.runtime.object_store import get_client

    try:
        get_client().free(list(refs))
    except Exception:  # noqa: BLE001 - a stopping runtime reads as freed
        pass


class FrameEstimatorInterface(ABC):
    """``fit_on_frame`` — the ``fit_on_spark`` analogue
    (spark/interfaces.py:27-39): accepts ETL DataFrames, converts through the
    data plane (object store or a parquet spill directory), optionally stops the
    ETL engine after conversion with ownership transferred to the master."""

    @abstractmethod
    def fit_on_frame(self, train_df, evaluate_df=None, *,
                     fs_directory: Optional[str] = None,
                     stop_etl_after_conversion: bool = False,
                     max_retries: int = 0):
        ...

    @staticmethod
    def _convert_frames(train_df, evaluate_df=None, *,
                        fs_directory: Optional[str] = None,
                        stop_etl_after_conversion: bool = False):
        """Frames → datasets through the chosen conversion path; optionally
        stop the ETL engine with ownership transferred to the master so the
        data survives (parity: torch/estimator.py:358-390, dataset.py:137-158).
        Shared by every concrete estimator's ``fit_on_frame``."""
        import raydp_tpu
        from raydp_tpu.data import from_frame, from_frame_recoverable

        def convert(df, tag):
            if df is None:
                return None
            if fs_directory is not None:
                # parquet spill path (parity: torch/estimator.py:365-376)
                path = os.path.join(fs_directory, tag)
                df.write.parquet(path)
                session = df._session
                return from_frame(session.read.parquet(path))
            return from_frame_recoverable(df)

        train_ds = convert(train_df, "train")
        eval_ds = convert(evaluate_df, "eval")
        if stop_etl_after_conversion:
            train_ds.transfer_to_master()
            if eval_ds is not None:
                eval_ds.transfer_to_master()
            raydp_tpu.stop(cleanup_data=False)
        return train_ds, eval_ds


def save_epoch_now(epoch: int, interval: int, num_epochs: int) -> bool:
    """The checkpoint cadence every estimator loop shares: every
    ``interval``-th epoch, and always the final one (so resume/get_model
    semantics hold at any interval)."""
    return (epoch + 1) % interval == 0 or epoch == num_epochs - 1
