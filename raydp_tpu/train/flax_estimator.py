"""FlaxEstimator: the TorchEstimator-parity trainer, pjit-compiled for TPU.

Parity map (reference torch/estimator.py):

- model/optimizer/loss as instances **or** creator callables (177-220) — here a
  Flax module (or creator), an optax transformation (or creator), and a loss
  callable or name.
- ``fit``: per-epoch train/evaluate loops with metric reporting (272-310) — here
  one jitted SPMD step; the DDP wrap + allreduce (243) is replaced by sharding
  annotations: batch sharded over the mesh's data axes, params replicated (or
  fsdp-sharded), XLA inserting the gradient ``psum`` over ICI.
- rank-0 checkpoint per epoch via Ray Train Checkpoint (259-270) — here orbax,
  saved by process 0.
- ``fit(..., max_retries)`` / ``FailureConfig`` (312-356) — here the epoch loop
  resumes from the last orbax checkpoint on failure, which is *stronger* than the
  reference's replay-from-scratch (SURVEY.md §5 checkpoint/resume gap).
- ``fit_on_spark`` with object-store or parquet-spill conversion and optional
  ``stop_spark_after_conversion`` + ownership transfer (358-390) —
  ``fit_on_frame`` below mirrors all three.
"""

from __future__ import annotations

import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from raydp_tpu import faults, knobs
from raydp_tpu.log import get_logger
from raydp_tpu.train.estimator import (
    EstimatorInterface,
    FrameEstimatorInterface,
    save_epoch_now,
)
from raydp_tpu.train.metrics import Metric, build_metrics

logger = get_logger("train.flax_estimator")


@dataclass
class TrainingResult:
    state: Any
    history: List[Dict[str, float]] = field(default_factory=list)
    checkpoint_dir: Optional[str] = None

    @property
    def final_metrics(self) -> Dict[str, float]:
        return self.history[-1] if self.history else {}


def _takes_train(model) -> bool:
    """Does the module's __call__ accept a ``train`` kwarg (dropout/BN mode)?
    Shared by the train loop and predict so both pass the same kwargs."""
    import inspect

    try:
        return "train" in inspect.signature(type(model).__call__).parameters
    except (TypeError, ValueError):
        return False


def _cast_floating(inputs, dtype):
    """Cast the floating leaves of a batch pytree to the compute dtype —
    THE cast policy, shared by the train loop and predict."""
    if dtype is None:
        return inputs
    import jax
    import jax.numpy as jnp

    return jax.tree.map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, inputs)


def _masked_mean(x, mask):
    """Mean of ``x`` over REAL rows only: per-row reduce the non-batch dims,
    then weight by the 0/1 mask. ``mask=None`` is a plain mean — bit-for-bit
    the pre-mask loss, so unpadded feeds are untouched."""
    import jax.numpy as jnp

    if mask is None:
        return jnp.mean(x)
    if x.ndim > 1:
        x = jnp.mean(x, axis=tuple(range(1, x.ndim)))
    return jnp.sum(x * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def _resolve_loss(loss) -> Callable:
    import jax.numpy as jnp

    if callable(loss):
        return loss
    name = (loss or "mse").lower()

    # every named loss is elementwise-then-_masked_mean so a pad-and-mask
    # feed's zero rows contribute nothing (mask=None reduces identically
    # to the plain mean)
    def mse(preds, labels, mask=None):
        return _masked_mean((preds - labels) ** 2, mask)

    def mae(preds, labels, mask=None):
        return _masked_mean(jnp.abs(preds - labels), mask)

    def smooth_l1(preds, labels, beta=1.0, mask=None):
        # parity: the reference's NYCTaxi example trains with SmoothL1Loss
        # (examples/pytorch_nyctaxi.py:69-105)
        d = jnp.abs(preds - labels)
        return _masked_mean(jnp.where(d < beta, 0.5 * d * d / beta,
                                      d - 0.5 * beta), mask)

    def bce_with_logits(logits, labels, mask=None):
        return _masked_mean(jnp.clip(logits, 0) - logits * labels
                            + jnp.log1p(jnp.exp(-jnp.abs(logits))), mask)

    def softmax_cross_entropy(logits, labels, mask=None):
        import optax
        return _masked_mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, labels.astype(jnp.int32)), mask)

    table = {"mse": mse, "l2": mse, "mae": mae, "l1": mae,
             "smooth_l1": smooth_l1, "huber": smooth_l1,
             "bce": bce_with_logits, "bce_with_logits": bce_with_logits,
             "cross_entropy": softmax_cross_entropy}
    if name not in table:
        raise ValueError(f"unknown loss {name!r}; have {sorted(table)}")
    return table[name]


def _loss_takes_mask(loss) -> bool:
    """Can this loss spec weight out padded rows? Named losses all can; a
    user callable must accept a ``mask`` kwarg — otherwise the feed falls
    back to dropping the tail (never silently mis-averaging pad zeros)."""
    if not callable(loss):
        return True
    import inspect

    try:
        return "mask" in inspect.signature(loss).parameters
    except (TypeError, ValueError):
        return False


def _strip_mask(batch):
    """Split the feed's validity mask off a batch dict (None when the feed
    is not padding) — model/preprocessor code never sees the mask key."""
    from raydp_tpu.data.feed import MASK_KEY

    mask = batch.get(MASK_KEY)
    if mask is None:
        return batch, None
    return {k: v for k, v in batch.items() if k != MASK_KEY}, mask


def _update_metric(m, stats, preds, labels, mask):
    """Metric update with the mask passed ONLY when one exists: builtin
    metrics take it; a custom Metric without mask support keeps working on
    unpadded feeds and fails loudly (not silently wrong) on padded ones."""
    if mask is None:
        return m.update(stats, preds, labels)
    return m.update(stats, preds, labels, mask=mask)


def _make_apply(model, takes_train, split_batch, compute_dtype):
    """Build THE forward used by fit's train/eval steps and partial_fit —
    one source for the split/cast/mutable-batch-stats/squeeze policy, so the
    online twin cannot drift from the epoch loop.

    Returns ``apply_fn(params, bstats, batch, train) ->
    (preds_f32, labels, new_bstats)``."""
    import jax.numpy as jnp

    def apply_fn(params, bstats, batch, train: bool):
        inputs, labels = split_batch(batch)
        inputs = _cast_floating(inputs, compute_dtype)
        variables = {"params": params}
        kwargs = {"train": train} if takes_train else {}
        if bstats is not None:
            variables["batch_stats"] = bstats
            if train:
                preds, updates = model.apply(
                    variables, inputs, mutable=["batch_stats"], **kwargs)
                new_bstats = updates["batch_stats"]
            else:
                preds = model.apply(variables, inputs, **kwargs)
                new_bstats = bstats
        else:
            preds = model.apply(variables, inputs, **kwargs)
            new_bstats = None
        if preds.ndim == labels.ndim + 1 and preds.shape[-1] == 1:
            preds = preds.squeeze(-1)
        return preds.astype(jnp.float32), labels, new_bstats

    return apply_fn


class PipelineModel:
    """A layer-list model description for pipeline-parallel placement.

    ``layers`` is a sequence of stage-homogeneous Flax modules (identical
    parameter structure and shapes — the transformer-block case); ``embed``
    and ``head`` are optional entry/exit modules that run OUTSIDE the
    pipeline (embed must map the batch inputs to the hidden array the blocks
    consume). On a mesh with ``stage > 1`` the estimator stacks the per-layer
    parameter pytrees via
    :func:`raydp_tpu.parallel.pipeline.stack_stage_params` onto a leading
    ``stage_stack`` axis (role-driven specs shard it over ``stage``) and runs
    the blocks through the ``shard_map`` GPipe schedule; on ``stage == 1``
    meshes the same description trains through a sequential ``vmap`` fallback
    — one model description, any mesh.

    ``init``/``apply`` mirror the Flax module surface the estimator and the
    serving bundle consume (``apply`` is the host-side sequential form used
    by ``predict``/``export_serving`` — row-identical to the pipelined
    forward). BatchNorm-style mutable collections are not supported in the
    blocks (``init`` raises: running stats cannot hop stages).
    """

    def __init__(self, layers, embed=None, head=None):
        if not layers:
            raise ValueError("PipelineModel needs at least one layer")
        self.layers = list(layers)
        self.embed = embed
        self.head = head

    def init(self, rng, inputs):
        import jax

        from raydp_tpu.parallel.pipeline import stack_stage_params

        params: Dict[str, Any] = {}
        h = inputs
        if self.embed is not None:
            rng, k = jax.random.split(rng)
            v = self.embed.init(k, h)
            self._reject_mutable(v, "embed")
            params["embed"] = v["params"]
            h = self.embed.apply({"params": params["embed"]}, h)
        layer_params = []
        for i, layer in enumerate(self.layers):
            rng, k = jax.random.split(rng)
            v = layer.init(k, h)
            self._reject_mutable(v, f"layers[{i}]")
            layer_params.append(v["params"])
            h = layer.apply({"params": v["params"]}, h)
        # jnp.stack raises on shape mismatch — the stage-homogeneity check
        params["stage_stack"] = stack_stage_params(layer_params)
        if self.head is not None:
            rng, k = jax.random.split(rng)
            v = self.head.init(k, h)
            self._reject_mutable(v, "head")
            params["head"] = v["params"]
        return {"params": params}

    @staticmethod
    def _reject_mutable(variables, where: str):
        extra = sorted(set(variables) - {"params"})
        if extra:
            raise ValueError(
                f"PipelineModel {where} carries mutable collections {extra} "
                f"(e.g. BatchNorm batch_stats): running stats cannot hop "
                f"pipeline stages — use stat-free blocks (LayerNorm)")

    def apply(self, variables, inputs):
        """Host/serving forward: the layers applied sequentially from the
        stacked tree — the exact math of the pipelined forward, one device."""
        import jax

        p = variables["params"]
        h = inputs
        if self.embed is not None:
            h = self.embed.apply({"params": p["embed"]}, h)
        stack = p["stage_stack"]
        n_layers = int(jax.tree.leaves(stack)[0].shape[0])
        block = self.layers[0]
        for i in range(n_layers):
            h = block.apply(
                {"params": jax.tree.map(lambda a: a[i], stack)}, h)
        if self.head is not None:
            h = self.head.apply({"params": p["head"]}, h)
        return h


def _make_pipeline_apply(model: "PipelineModel", split_batch, compute_dtype,
                         mesh, n_micro: int, seg_modes: Dict[str, str]):
    """The pipeline twin of :func:`_make_apply`: same
    ``apply_fn(params, bstats, batch, train) -> (preds_f32, labels, None)``
    signature, but the forward splits the batch into ``n_micro`` microbatches
    and marches them through the ``shard_map`` GPipe schedule
    (:func:`raydp_tpu.parallel.pipeline.pipeline_apply`).

    This is where accumulation and pipeline microbatching UNIFY: the
    estimator's ``accum_steps`` microbatches ARE the pipeline's microbatches
    — one ``lax.scan`` of ``n_micro + n_stages - 1`` ticks runs the whole
    forward, and AD of it is the reverse pipeline, so the train step wraps
    this forward with ``accum=1`` (a second scan would re-microbatch the
    microbatches). ``seg_modes`` maps each segment (``embed`` /
    ``stage_stack`` / ``head``) to its remat mode — the per-role policy
    resolved against each segment's dominant parameter role.
    """
    import jax
    import jax.numpy as jnp

    from raydp_tpu.parallel.pipeline import pipeline_apply
    from raydp_tpu.parallel.roles import apply_remat

    embed_mod, head_mod, block = model.embed, model.head, model.layers[0]

    def _block_fwd(p, x):
        return block.apply({"params": p}, x)

    block_fwd = apply_remat(_block_fwd, seg_modes.get("stage_stack", "none"))
    embed_fwd = head_fwd = None
    if embed_mod is not None:
        embed_fwd = apply_remat(
            lambda p, x: embed_mod.apply({"params": p}, x),
            seg_modes.get("embed", "none"))
    if head_mod is not None:
        head_fwd = apply_remat(
            lambda p, x: head_mod.apply({"params": p}, x),
            seg_modes.get("head", "none"))

    def apply_fn(params, bstats, batch, train: bool):
        del bstats, train  # pipeline blocks are stat-free and mode-free
        inputs, labels = split_batch(batch)
        inputs = _cast_floating(inputs, compute_dtype)
        h = embed_fwd(params["embed"], inputs) if embed_fwd is not None \
            else inputs
        rows = int(h.shape[0])
        if rows % n_micro:
            raise ValueError(
                f"pipeline microbatching: accum_steps={n_micro} does not "
                f"divide the batch dimension {rows} — pad-and-mask the tail "
                f"(RDT_TRAIN_PAD_TAIL) or drop it (drop_last=True)")
        h_micro = h.reshape((n_micro, rows // n_micro) + h.shape[1:])
        out = pipeline_apply(block_fwd, params["stage_stack"], h_micro, mesh)
        h2 = out.reshape((rows,) + out.shape[2:])
        preds = head_fwd(params["head"], h2) if head_fwd is not None else h2
        if preds.ndim == labels.ndim + 1 and preds.shape[-1] == 1:
            preds = preds.squeeze(-1)
        return preds.astype(jnp.float32), labels, None

    return apply_fn


def _make_train_step(apply_fn, loss_fn, metrics, accum: int, remat_mode: str,
                     mb_shardings=None):
    """Build the jitted train-step body shared by ``fit`` and
    ``partial_fit``: one optimizer update from one global batch.

    With ``accum > 1`` the batch reshapes to ``[accum, B/accum, ...]``
    microbatches folded through a ``lax.scan``: per-microbatch grads, loss
    and metric stats accumulate ROW-WEIGHTED (a masked microbatch — even an
    all-pad one from a pad-and-mask tail — weighs in by its real rows), so
    the single ``apply_gradients`` at the end reproduces the unaccumulated
    update to float-summation-order tolerance while only ONE microbatch's
    activations are ever live. ``remat_mode`` wraps the forward in
    ``jax.checkpoint`` per :func:`raydp_tpu.parallel.roles.apply_remat`;
    both knobs together are the activation-residency lever the
    ``mesh_bench --activation`` record measures.

    ``mb_shardings`` — optional ``(batch_sharding, seq_sharding)`` pair
    (seq may be None) re-asserted on every microbatch inside the scan: the
    ``[B, ...] → [accum, B/accum, ...]`` reshape breaks GSPMD sharding
    propagation, and without the constraint XLA gathers each microbatch
    onto every data shard — erasing most of the residency win the
    accumulation exists for (measured 4× worse peak temp bytes on an 8-way
    mesh). Leaf rule matches the feed's: ndim >= 2 leaves take the
    seq-extended spec, 1-D leaves (labels, masks) the plain batch spec.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from raydp_tpu.parallel.roles import apply_remat

    def _microbatch_grads(params, bstats, batch, mask):
        def _loss(p):
            preds, labels, new_bstats = apply_fn(p, bstats, batch, train=True)
            lv = loss_fn(preds, labels, mask=mask) if mask is not None \
                else loss_fn(preds, labels)
            return lv, (preds, labels, new_bstats)

        fwd = apply_remat(_loss, remat_mode)
        return jax.value_and_grad(fwd, has_aux=True)(params)

    def train_step(state, batch, mstats, loss_sum):
        batch, mask = _strip_mask(batch)
        if accum <= 1:
            (loss_val, (preds, labels, new_bstats)), grads = \
                _microbatch_grads(state.params, state.batch_stats, batch,
                                  mask)
            new_state = state.apply_gradients(grads=grads)
            if new_bstats is not None:
                new_state = new_state.replace(batch_stats=new_bstats)
            new_mstats = tuple(
                _update_metric(m, s, preds, labels, mask)
                for m, s in zip(metrics, mstats))
            return (new_state, loss_sum + loss_val.astype(jnp.float32),
                    new_mstats)

        def _split(a):
            if a.shape[0] % accum:
                raise ValueError(
                    f"accum_steps={accum} does not divide the batch "
                    f"dimension {a.shape[0]}")
            return a.reshape((accum, a.shape[0] // accum) + a.shape[1:])

        micro = jax.tree.map(_split, batch)
        micro_mask = None if mask is None else _split(mask)
        # grads/loss accumulate in f32 regardless of the param dtype: k-1
        # additions in bf16 would lose exactly the low bits the parity
        # contract (accum=k == accum=1 to tolerance) protects
        g0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                          state.params)
        ms0 = jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), mstats)

        def body(carry, xs):
            g_acc, l_acc, r_acc, bstats, ms = carry
            mb = xs[0]
            mb_mask = xs[1] if micro_mask is not None else None
            if mb_shardings is not None:
                b_sh, s_sh = mb_shardings
                mb = jax.tree.map(
                    lambda a: lax.with_sharding_constraint(
                        a, s_sh if s_sh is not None and a.ndim >= 2
                        else b_sh), mb)
                if mb_mask is not None:
                    mb_mask = lax.with_sharding_constraint(mb_mask, b_sh)
            (lv, (preds, labels, new_bstats)), g = _microbatch_grads(
                state.params, bstats, mb, mb_mask)
            rows = jnp.sum(mb_mask) if mb_mask is not None \
                else jnp.float32(labels.shape[0])
            g_acc = jax.tree.map(
                lambda a, gg: a + gg.astype(jnp.float32) * rows, g_acc, g)
            l_acc = l_acc + lv.astype(jnp.float32) * rows
            r_acc = r_acc + rows
            ms = tuple(_update_metric(m, s, preds, labels, mb_mask)
                       for m, s in zip(metrics, ms))
            return (g_acc, l_acc, r_acc, new_bstats, ms), ()

        xs = (micro,) if micro_mask is None else (micro, micro_mask)
        carry0 = (g0, jnp.float32(0), jnp.float32(0), state.batch_stats, ms0)
        (g_acc, l_acc, r_acc, new_bstats, new_mstats), _ = lax.scan(
            body, carry0, xs)
        denom = jnp.maximum(r_acc, 1.0)
        grads = jax.tree.map(lambda a, p: (a / denom).astype(p.dtype),
                             g_acc, state.params)
        new_state = state.apply_gradients(grads=grads)
        if new_bstats is not None:
            new_state = new_state.replace(batch_stats=new_bstats)
        return new_state, loss_sum + l_acc / denom, new_mstats

    return train_step


class FlaxEstimator(EstimatorInterface, FrameEstimatorInterface):
    def __init__(
        self,
        model=None,
        model_creator: Optional[Callable] = None,
        optimizer=None,
        optimizer_creator: Optional[Callable] = None,
        loss: Union[str, Callable, None] = "mse",
        feature_columns: Optional[Sequence[str]] = None,
        label_column: Optional[str] = None,
        batch_size: int = 64,
        num_epochs: int = 10,
        mesh=None,
        mesh_spec=None,
        metrics: Optional[Sequence[Union[str, Metric]]] = None,
        checkpoint_dir: Optional[str] = None,
        seed: int = 0,
        feature_dtype=np.float32,
        label_dtype=np.float32,
        shuffle: bool = True,
        param_rules=None,
        batch_preprocessor: Optional[Callable] = None,
        columns_spec: Optional[Dict] = None,
        compute_dtype=None,
        drop_last: bool = True,
        callbacks: Optional[Sequence[Callable[[Dict], None]]] = None,
        steps_per_dispatch: int = 1,
        checkpoint_interval: int = 1,
        prefetch_to_device: Optional[int] = None,
        accum_steps: Optional[int] = None,
        remat: Optional[str] = None,
        seq_sharded: Optional[bool] = None,
    ):
        if model is None and model_creator is None:
            raise ValueError("pass model or model_creator")
        self._model = model
        self._model_creator = model_creator
        self._optimizer = optimizer
        self._optimizer_creator = optimizer_creator
        self._loss = loss
        self.feature_columns = list(feature_columns or [])
        self.label_column = label_column
        self.batch_size = batch_size
        self.num_epochs = num_epochs
        self._mesh = mesh
        self._mesh_spec = mesh_spec
        self._metrics = build_metrics(metrics or [])
        self.checkpoint_dir = checkpoint_dir
        self.seed = seed
        self.feature_dtype = feature_dtype
        self.label_dtype = label_dtype
        self.shuffle = shuffle
        self.param_rules = param_rules
        self.batch_preprocessor = batch_preprocessor
        self.columns_spec = columns_spec
        self.compute_dtype = compute_dtype
        self.drop_last = drop_last
        self.callbacks = list(callbacks or [])
        #: chain this many train steps inside ONE jitted dispatch (lax.scan
        #: over a stacked batch). Numerically identical to dispatching each
        #: batch (same update sequence); the win is k× fewer host→device
        #: round trips, which dominate on a remote-tunnel TPU (~64 ms each).
        self.steps_per_dispatch = max(1, int(steps_per_dispatch))
        #: checkpoint every N-th epoch (the final epoch always saves). The
        #: reference checkpoints per epoch (default 1 keeps that); with the
        #: device-resident path an epoch can be cheaper than its checkpoint,
        #: so long runs may want a sparser cadence — a retry/resume then
        #: replays at most N-1 epochs from the last save.
        self.checkpoint_interval = max(1, int(checkpoint_interval))
        #: device-placed batches the streaming feed keeps ahead of the train
        #: step (None = the feed default / RDT_PREFETCH_TO_DEVICE, 2): H2D
        #: for batch k+1 overlaps the compute of batch k — bit-identical to
        #: synchronous placement (tests/test_feed_pipeline.py). The
        #: device-resident path ignores it (nothing streams).
        self.prefetch_to_device = prefetch_to_device
        #: gradient-accumulation microbatches per optimizer step (None = the
        #: RDT_TRAIN_ACCUM_STEPS knob, default 1). k splits every global
        #: batch into k scanned microbatches whose row-weighted grad/loss/
        #: metric accumulation reproduces the unaccumulated update while
        #: only one microbatch's activations are live — peak activation
        #: bytes drop ~k×. Must divide batch_size.
        self.accum_steps = accum_steps
        #: rematerialization policy for the train-step forward: a global
        #: mode ('none' | 'dots' | 'full' — the default policy) or a
        #: per-role 'role=mode,...' map over the param roles
        #: ('embedding=none,kernel=dots,default=full'); None = the
        #: RDT_TRAIN_REMAT knob. jax.checkpoint placement per
        #: parallel/roles.py parse_remat_policy / remat_policy
        self.remat = remat
        #: shard declared sequence dims (dim 1 of ndim >= 2 batch leaves)
        #: over the mesh's ``seq`` axis (None = auto: on whenever the mesh
        #: has a >1 seq extent). Layout-only — results stay row-identical.
        self.seq_sharded = seq_sharded
        self._result: Optional[TrainingResult] = None

    def _resolve_accum(self) -> int:
        """The effective accumulation factor for THIS fit (the constructor
        argument wins over the knob; knob read at call time — per-action
        scope). Validated against batch_size: k must slice the global batch
        into equal microbatches or the scanned program cannot reshape it."""
        k = self.accum_steps if self.accum_steps is not None \
            else int(knobs.get("RDT_TRAIN_ACCUM_STEPS"))
        k = max(1, int(k))
        if k > 1 and self.batch_size % k:
            raise ValueError(
                f"accum_steps={k} must divide batch_size={self.batch_size}")
        return k

    def _resolve_remat(self) -> Dict[str, str]:
        """The effective remat POLICY for THIS fit: a role→mode map parsed
        (and validated, eagerly — long before any compile) by
        :func:`raydp_tpu.parallel.roles.parse_remat_policy`. A bare mode
        string (the pre-r20 global form) parses to ``{"default": mode}`` —
        the global mode IS the default policy, so old specs behave
        identically; ``"embedding=none,kernel=dots"`` picks per parameter
        role the way the param specs are picked."""
        from raydp_tpu.parallel.roles import parse_remat_policy

        spec = (self.remat if self.remat is not None
                else str(knobs.get("RDT_TRAIN_REMAT"))).lower()
        return parse_remat_policy(spec)

    def _make_forward(self, model, mesh, takes_train, params):
        """Build THIS fit's forward + the train-step knobs around it — ONE
        source shared by ``fit`` and ``partial_fit`` so the two cannot drift.

        Returns ``(apply_fn, step_accum, step_remat, n_micro, n_stages)``:
        the forward with :func:`_make_apply`'s signature, the accumulation
        factor and remat mode ``_make_train_step`` should apply AROUND it,
        and the pipeline geometry. For a :class:`PipelineModel` the forward
        is the GPipe schedule with the resolved ``accum_steps`` as its
        microbatch count — so ``step_accum`` is 1 and ``step_remat`` is
        ``none`` (microbatching and remat both live INSIDE the pipelined
        forward, per segment); a monolithic model keeps the scan-around-
        the-forward shape, its mode resolved from the params' dominant
        role under the per-role policy."""
        from raydp_tpu.parallel.mesh import stage_extent
        from raydp_tpu.parallel.roles import (remat_mode_for_role,
                                              segment_role)

        accum = self._resolve_accum()
        policy = self._resolve_remat()
        n_stages = stage_extent(mesh)
        if isinstance(model, PipelineModel):
            n_layers = len(model.layers)
            if n_stages > 1 and n_layers % n_stages:
                raise ValueError(
                    f"PipelineModel has {n_layers} layers; the mesh's "
                    f"stage={n_stages} must divide them (each stage applies "
                    f"a contiguous run of layers)")
            seg_modes = {
                name: remat_mode_for_role(policy, segment_role(sub))
                for name, sub in params.items()}
            papply = _make_pipeline_apply(model, self._split_batch,
                                          self.compute_dtype, mesh, accum,
                                          seg_modes)
            return papply, 1, "none", accum, n_stages
        if n_stages > 1:
            raise ValueError(
                f"mesh has stage={n_stages} but the model is not a "
                f"PipelineModel: stage-stacked placement needs the "
                f"layer-list description (raydp_tpu.train.PipelineModel)")
        mode = remat_mode_for_role(policy, segment_role(params))
        apply_fn = _make_apply(model, takes_train, self._split_batch,
                               self.compute_dtype)
        return apply_fn, accum, mode, accum, 1

    def _use_seq(self, mesh) -> bool:
        """Does THIS fit extend batch shardings over the mesh's seq axis?
        Auto-on when the mesh has a >1 seq extent; ``seq_sharded=False``
        opts out (and True without a seq extent stays off — there is
        nothing to shard over)."""
        from raydp_tpu.parallel.mesh import seq_extent

        if seq_extent(mesh) <= 1:
            return False
        return True if self.seq_sharded is None else bool(self.seq_sharded)

    # ------------------------------------------------------------------ build
    def _build_model(self):
        return self._model if self._model is not None else self._model_creator()

    def _build_optimizer(self):
        import optax
        if self._optimizer is not None:
            return self._optimizer
        if self._optimizer_creator is not None:
            return self._optimizer_creator()
        return optax.adam(1e-3)

    def _build_mesh(self):
        if self._mesh is not None:
            return self._mesh
        from raydp_tpu.parallel import make_mesh
        return make_mesh(self._mesh_spec)

    def _columns(self) -> Dict:
        if self.columns_spec is not None:
            return self.columns_spec
        if not self.feature_columns or self.label_column is None:
            raise ValueError("pass feature_columns + label_column or columns_spec")
        return {
            "features": (self.feature_columns, self.feature_dtype),
            "label": (self.label_column, self.label_dtype),
        }

    def _split_batch(self, batch: Dict):
        if self.batch_preprocessor is not None:
            return self.batch_preprocessor(batch)
        return batch["features"], batch["label"]

    # -------------------------------------------------------------------- fit
    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0
            ) -> TrainingResult:
        from raydp_tpu.data.feed import DeviceEpochCache, DeviceFeed

        mesh = self._build_mesh()
        columns = self._columns()
        ckpt_dir = self.checkpoint_dir or tempfile.mkdtemp(prefix="rdt-ckpt-")

        # pad-and-mask rule, decided HERE for every feed below so train and
        # eval cannot disagree: under a >1 data extent a ragged tail pads to
        # a full (shardable) batch and carries a validity mask instead of
        # silently dropping rows. A >1 STAGE extent needs the same rule for
        # a different reason: the pipelined forward reshapes every batch
        # into accum_steps microbatches, so a ragged tail must pad to the
        # (divisible) full batch — its pad rows mask out of the loss exactly
        # like dp pad rows. RDT_TRAIN_PAD_TAIL=0 — or a custom loss with no
        # mask kwarg — restores the drop behavior.
        from raydp_tpu.parallel.mesh import data_axes, stage_extent
        dp_total = int(np.prod([mesh.shape[a] for a in data_axes(mesh)]))
        stage_total = stage_extent(mesh)
        pad_tail = ((dp_total > 1 or stage_total > 1)
                    and bool(knobs.get("RDT_TRAIN_PAD_TAIL"))
                    and _loss_takes_mask(self._loss))
        use_seq = self._use_seq(mesh)

        # device-resident fast path: dataset pinned in HBM, whole epoch in one
        # jitted dispatch with on-device shuffling (falls back to the
        # streaming feed when too large / multi-process / ragged-batch)
        cache = feed = None
        if DeviceEpochCache.eligible(train_ds, columns, self.batch_size,
                                     self.drop_last):
            cache = DeviceEpochCache(train_ds, columns, mesh=mesh)
        if cache is None:
            feed = DeviceFeed(train_ds, self.batch_size, columns, mesh=mesh,
                              shuffle=self.shuffle, seed=self.seed,
                              drop_remainder=self.drop_last,
                              pad_remainder=pad_tail and not self.drop_last,
                              prefetch_to_device=self.prefetch_to_device,
                              seq=use_seq)
        eval_feed = eval_cache = None
        eval_tail_ok = False
        if evaluate_ds is not None:
            # the ragged final batch: fine as-is under a size-1 data extent
            # (and no pipeline — a stage>1 forward cannot reshape a ragged
            # batch), pad-and-masked under a >1 one (dropped only when
            # padding is opted out — the pre-PR-16 behavior)
            eval_tail_ok = (dp_total == 1 and stage_total == 1) or pad_tail
            # eval goes resident alongside the train set: the whole eval
            # pass becomes one scan dispatch (+ one for the ragged tail)
            # instead of one dispatch per batch, every epoch. The budget is
            # COMBINED: train + eval residency together stay under the cap
            if (cache is not None
                    and DeviceEpochCache.eligible(evaluate_ds, columns,
                                                  1, True)
                    and cache.nbytes + DeviceEpochCache.estimate_bytes(
                        evaluate_ds, columns) <= DeviceEpochCache.cap_bytes()):
                eval_cache = DeviceEpochCache(evaluate_ds, columns, mesh=mesh)
            else:
                eval_feed = DeviceFeed(evaluate_ds, self.batch_size, columns,
                                       mesh=mesh, shuffle=False,
                                       drop_remainder=not eval_tail_ok,
                                       pad_remainder=pad_tail,
                                       prefetch_to_device=self.prefetch_to_device,
                                       seq=use_seq)

        state, history = self._train_loop(
            mesh, feed, eval_feed, ckpt_dir, max_retries=max_retries,
            cache=cache, eval_cache=eval_cache, eval_tail_ok=eval_tail_ok,
            eval_tail_pad=pad_tail)
        self._result = TrainingResult(state=state, history=history,
                                      checkpoint_dir=ckpt_dir)
        return self._result

    @staticmethod
    def _place_state(tree, shardings):
        """Place a host pytree under global shardings (see
        :func:`raydp_tpu.train.checkpoint.place_tree`)."""
        from raydp_tpu.train import checkpoint as ckpt
        return ckpt.place_tree(tree, shardings)

    def _train_loop(self, mesh, feed, eval_feed, ckpt_dir: str,
                    max_retries: int = 0, resume: bool = False, cache=None,
                    eval_cache=None, eval_tail_ok: bool = False,
                    eval_tail_pad: bool = False):
        import jax
        import jax.numpy as jnp
        import optax
        from flax.training import train_state

        from raydp_tpu.parallel import batch_sharding, param_sharding_rules
        from raydp_tpu.train import checkpoint as ckpt

        if not resume and self.checkpoint_dir:
            ckpt.warn_if_reused_dir(ckpt_dir)
        model = self._build_model()
        tx = self._build_optimizer()
        loss_fn = _resolve_loss(self._loss)
        metrics = self._metrics

        # ---- init params from one host batch's shapes ----
        first = cache.init_row if cache is not None \
            else next(iter(feed.host_iter))
        inputs0, _ = self._split_batch(
            {k: jnp.asarray(v[:1]) for k, v in first.items()})
        rng = jax.random.PRNGKey(self.seed)
        takes_train = _takes_train(model)
        init_kwargs = {"train": False} if takes_train else {}
        variables = model.init(rng, inputs0, **init_kwargs)
        batch_stats = variables.get("batch_stats")

        class _State(train_state.TrainState):
            # models with BatchNorm carry running stats beside params
            batch_stats: Any = None

        state = _State.create(
            apply_fn=model.apply, params=variables["params"], tx=tx,
            batch_stats=batch_stats)

        shardings_of = param_sharding_rules(mesh, self.param_rules)
        state_sharding = shardings_of(state)
        from raydp_tpu import metrics as rdt_metrics
        from raydp_tpu import profiler
        from raydp_tpu.parallel.roles import addressable_nbytes
        with profiler.trace("train:place", "training"):
            state = self._place_state(state, state_sharding)
        # the fsdp memory claim, observed where it is true: bytes of params
        # + optimizer state resident on THIS process's devices after
        # placement (replicated leaves count one copy per device)
        rdt_metrics.set_gauge("train_param_bytes_per_process",
                            addressable_nbytes(state))
        b_sharding = batch_sharding(mesh)
        # seq-extended sharding for ndim >= 2 batch leaves on the resident
        # path (the streaming DeviceFeed carries its own — decided in fit());
        # None when the mesh has no >1 seq extent
        seq_sharding = batch_sharding(mesh, seq=True) \
            if self._use_seq(mesh) else None

        # the activation-side plane: accumulation factor, remat policy and
        # (on a stage>1 mesh) the GPipe schedule, resolved per fit
        # (constructor args win over the PER_ACTION knobs). In pipeline mode
        # the accum microbatches ARE the pipeline microbatches — one scan —
        # so the step wraps the forward with accum=1/remat "none" (both live
        # inside the pipelined forward, per segment).
        _apply, step_accum, step_remat, accum, n_stages = self._make_forward(
            model, mesh, takes_train, state.params)
        pipelined = n_stages > 1 or isinstance(model, PipelineModel)
        rdt_metrics.set_gauge("train_accum_steps", accum)
        if pipelined:
            rdt_metrics.set_gauge("train_pipeline_stages", n_stages)

        # Loss accumulators are threaded THROUGH the jitted steps rather than
        # collected as a host-side list: under a multi-process gang, an eager
        # op over global arrays (e.g. jnp.stack of per-step losses) is a
        # cross-process computation that every process must dispatch in the
        # same order — a rank that is one step behind deadlocks the gang. With
        # in-jit accumulation the only host reads are float() of replicated
        # scalars at epoch end (also one fewer host sync single-process).
        train_step = _make_train_step(_apply, loss_fn, metrics, step_accum,
                                      step_remat,
                                      mb_shardings=(b_sharding, seq_sharding))

        # publish the compiled step's peak temp (activation) bytes when the
        # activation plane is engaged — the residency number accumulation/
        # remat/pipelining drive down, read off XLA's memory_analysis at
        # first dispatch. Best-effort: some backends lack the analysis, and
        # telemetry must never fail (or slow an un-engaged) fit.
        measured = [accum <= 1 and step_remat == "none" and not pipelined]
        _compile_span = "train:pipeline" if pipelined else "train:accum"

        def _note_activation(fn, *args):
            measured[0] = True
            try:
                with profiler.trace(_compile_span, "training"):
                    mem = fn.lower(*args).compile().memory_analysis()
                temp = getattr(mem, "temp_size_in_bytes", None)
                if temp is not None:
                    local = sum(1 for d in mesh.devices.flat
                                if d.process_index == jax.process_index())
                    rdt_metrics.set_gauge(
                        "train_activation_bytes_per_process",
                        int(temp) * max(1, local))
            except Exception:  # noqa: BLE001 - telemetry only
                pass

        # eval threads BOTH accumulators (row-weighted loss sum AND the row
        # count) through the jitted step: under pad-and-mask the real row
        # count is mask.sum(), known on device — a host-side shape[0] count
        # would bill padded rows into the eval mean
        def eval_step(state, batch, mstats, loss_sum, cnt_sum):
            batch, mask = _strip_mask(batch)
            preds, labels, _ = _apply(state.params, state.batch_stats, batch,
                                      train=False)
            if mask is None:
                rows = jnp.float32(labels.shape[0])
                loss_val = loss_fn(preds, labels).astype(jnp.float32)
            else:
                rows = jnp.sum(mask)
                loss_val = loss_fn(preds, labels,
                                   mask=mask).astype(jnp.float32)
            new_mstats = tuple(
                _update_metric(m, s, preds, labels, mask)
                for m, s in zip(metrics, mstats))
            return loss_sum + loss_val * rows, cnt_sum + rows, new_mstats

        jit_train = jax.jit(train_step, donate_argnums=(0, 3))
        jit_eval = jax.jit(eval_step, donate_argnums=(3, 4))

        chain = self.steps_per_dispatch
        jit_chain = None
        if chain > 1 and cache is None:
            from jax import lax

            def train_chain(state, batches, mstats, loss_sum):
                def body(carry, batch):
                    state, loss_sum, mstats = carry
                    state, loss_sum, mstats = train_step(
                        state, batch, mstats, loss_sum)
                    return (state, loss_sum, mstats), ()

                (state, loss_sum, mstats), _ = lax.scan(
                    body, (state, loss_sum, mstats), batches)
                return state, loss_sum, mstats

            jit_chain = jax.jit(train_chain, donate_argnums=(0, 3))

        jit_epoch = None
        cache_steps = 0
        if cache is not None:
            # device-resident path: the WHOLE epoch is one jitted dispatch
            # (the shared scan program built by DeviceEpochCache — one source
            # for the permutation/slice logic across estimators). Steady-state
            # host work per epoch: one dispatch + one scalar fetch.
            def _step(carry, batch):
                state, loss_sum, mstats = carry
                return train_step(state, batch, mstats, loss_sum)

            epoch_fn, cache_steps = cache.make_epoch_fn(
                _step, self.batch_size, self.shuffle,
                batch_sharding=b_sharding, seq_sharding=seq_sharding)
            jit_epoch = jax.jit(epoch_fn, donate_argnums=(0,))

        jit_eval_epoch = None
        eval_tail = None
        if eval_cache is not None:
            # the whole eval pass as ONE scan dispatch, built by the same
            # make_epoch_fn as the train scan (one source for the
            # slice/constraint/scan logic); the ragged tail travels as one
            # extra jitted call — as-is where a single data shard allows it,
            # zero-padded to a full batch with a validity mask under a >1
            # data extent (eval_tail_ok/eval_tail_pad, decided in fit()
            # beside the streaming feed's rule so the two cannot disagree).
            # The carry rides the state through unchanged — NOT donated (it
            # lives on into the next epoch)
            def _eval_scan_step(carry, batch):
                state, estats, esum, ecnt = carry
                esum, ecnt, estats = eval_step(state, batch, estats, esum,
                                               ecnt)
                return state, estats, esum, ecnt

            eval_epoch_fn, esteps = eval_cache.make_epoch_fn(
                _eval_scan_step, self.batch_size, shuffle=False,
                batch_sharding=b_sharding, seq_sharding=seq_sharding)
            jit_eval_epoch = jax.jit(eval_epoch_fn)
            tail_off = esteps * self.batch_size
            tail_rows = eval_cache.num_rows - tail_off
            if tail_rows > 0 and eval_tail_ok:
                eval_tail = {n: a[tail_off:]
                             for n, a in eval_cache.arrays.items()}
                if eval_tail_pad:
                    from raydp_tpu.data.feed import MASK_KEY
                    pad = self.batch_size - tail_rows
                    eval_tail = {
                        n: jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
                        for n, a in eval_tail.items()}
                    eval_tail[MASK_KEY] = (
                        jnp.arange(self.batch_size) < tail_rows
                    ).astype(jnp.float32)

        history: List[Dict[str, float]] = []
        epoch = 0
        retries = 0
        #: highest checkpoint step THIS run wrote — a retry may only restore
        #: up to it; a reused dir's stale steps (possibly HIGHER-numbered,
        #: which latest-step selection would otherwise prefer) are foreign
        last_written_step: Optional[int] = None
        if resume:
            restored = ckpt.restore_placed(ckpt_dir, state, state_sharding)
            if restored is not None:
                state, done_epoch = restored
                epoch = done_epoch + 1
                extra = ckpt.restore_extra(ckpt_dir)
                if extra and "history" in extra:
                    history = list(extra["history"])
                logger.info("resuming from checkpoint step %d", done_epoch)
        from raydp_tpu import profiler

        while epoch < self.num_epochs:
            try:
                rule = faults.check("estimator.epoch", key=str(epoch))
                if rule is not None:  # chaos tests provoke the retry path here
                    faults.apply(rule, "estimator.epoch")
                t0 = time.perf_counter()
                mstats = tuple(m.init() for m in metrics)
                loss_sum = np.zeros((), np.float32)
                steps, samples = 0, 0
                t_feed = t_disp = 0.0
                if cache is not None:
                    td = time.perf_counter()
                    ekey = jax.random.fold_in(
                        jax.random.PRNGKey(self.seed), epoch)
                    if not measured[0]:
                        _note_activation(jit_epoch, (state, loss_sum, mstats),
                                         cache.arrays, ekey)
                    state, loss_sum, mstats = jit_epoch(
                        (state, loss_sum, mstats), cache.arrays, ekey)
                    # dispatch is async: fetch the loss scalar INSIDE this
                    # window so dispatch_time_s carries the epoch's device
                    # time (otherwise the report's sync slot absorbs it and
                    # this path reads as "zero dispatch cost")
                    loss_sum = np.float32(loss_sum)
                    t_disp = time.perf_counter() - td
                    steps = cache_steps
                    samples = cache_steps * self.batch_size
                else:
                    feed.set_epoch(epoch)
                    it = feed.chained(chain) if chain > 1 else iter(feed)
                    while True:
                        tf = time.perf_counter()
                        item = next(it, None)
                        t_feed += time.perf_counter() - tf
                        if item is None:
                            break
                        td = time.perf_counter()
                        if chain > 1:
                            batches, k = item
                            if not measured[0]:
                                _note_activation(jit_chain, state, batches,
                                                 mstats, loss_sum)
                            state, loss_sum, mstats = jit_chain(
                                state, batches, mstats, loss_sum)
                        else:
                            k = 1
                            if not measured[0]:
                                _note_activation(jit_train, state, item,
                                                 mstats, loss_sum)
                            state, loss_sum, mstats = jit_train(
                                state, item, mstats, loss_sum)
                        t_disp += time.perf_counter() - td
                        steps += k
                        samples += self.batch_size * k
                # fetch the accumulated loss BEFORE reading the clock:
                # dispatch is async (and on a remote-tunnel backend even
                # block_until_ready can return early), so only a host scalar
                # fetch makes the epoch wall include the device work — without
                # it per-epoch throughput swings ~4x between runs
                ts = time.perf_counter()
                train_loss = float(loss_sum) / steps if steps else float("nan")
                t_sync = time.perf_counter() - ts
                dt = time.perf_counter() - t0
                # registry twin of the epoch report (metrics_report() sees
                # epoch walls without re-publishing the history dicts)
                from raydp_tpu import metrics as rdt_metrics
                rdt_metrics.observe("train_epoch_seconds", dt)
                # the feed's thread-side phase split (decode/stage/h2d): these
                # walls OVERLAP dispatch by design (that is the prefetch win),
                # so they attribute the epoch, they don't sum to it
                pipe = feed.timings.take() if feed is not None else {}
                report = {
                    "epoch": epoch,
                    "train_loss": train_loss,
                    "steps": steps,
                    "samples_per_s": samples / dt if dt > 0 else 0.0,
                    "epoch_time_s": dt,
                    "feed_time_s": t_feed,
                    "decode_time_s": pipe.get("decode", 0.0),
                    "stage_time_s": pipe.get("stage", 0.0),
                    "h2d_time_s": pipe.get("h2d", 0.0),
                    "dispatch_time_s": t_disp,
                    "sync_time_s": t_sync,
                }
                for m, s in zip(metrics, mstats):
                    report[f"train_{m.name}"] = m.compute(
                        jax.tree.map(np.asarray, s))

                if eval_feed is not None or eval_cache is not None:
                    estats = tuple(m.init() for m in metrics)
                    esum = np.zeros((), np.float32)
                    ecnt = np.zeros((), np.float32)
                    if eval_cache is not None:
                        _, estats, esum, ecnt = jit_eval_epoch(
                            (state, estats, esum, ecnt), eval_cache.arrays,
                            jax.random.PRNGKey(0))  # unused: shuffle=False
                        if eval_tail is not None:
                            esum, ecnt, estats = jit_eval(
                                state, eval_tail, estats, esum, ecnt)
                    else:
                        for batch in eval_feed:
                            esum, ecnt, estats = jit_eval(state, batch,
                                                          estats, esum, ecnt)
                    rows = float(ecnt)  # real rows only: pad rows mask to 0
                    report["eval_loss"] = (float(esum) / rows) if rows \
                        else float("nan")
                    for m, s in zip(metrics, estats):
                        report[f"eval_{m.name}"] = m.compute(
                            jax.tree.map(np.asarray, s))

                history.append(report)
                for cb in self.callbacks:
                    cb(report)
                logger.info("epoch %d: %s", epoch,
                            {k: (round(v, 5) if isinstance(v, float) else v)
                             for k, v in report.items()})
                if save_epoch_now(epoch, self.checkpoint_interval,
                                  self.num_epochs):
                    ckpt.save(ckpt_dir, state, step=epoch,
                              extra={"history": history})
                    last_written_step = epoch
                epoch += 1
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # noqa: BLE001 - retry path (FailureConfig)
                retries += 1
                if retries > max_retries:
                    raise
                logger.warning("epoch %d failed (%s); restoring from checkpoint "
                               "(retry %d/%d)", epoch, e, retries, max_retries)
                # adopt a checkpoint only if an explicit resume claimed the
                # dir, or THIS run wrote it — and then only up to the step
                # this run wrote (a reused dir's stale higher-numbered steps
                # would otherwise win latest-step selection and silently
                # return an earlier run's model)
                if resume:
                    restored = ckpt.restore_placed(ckpt_dir, state,
                                                   state_sharding)
                elif last_written_step is not None:
                    restored = ckpt.restore_placed(
                        ckpt_dir, state, state_sharding,
                        max_step=last_written_step)
                else:
                    restored = None
                if restored is not None:
                    state, done_epoch = restored
                    epoch = done_epoch + 1
                    extra = ckpt.restore_extra(
                        ckpt_dir,
                        max_step=None if resume else last_written_step)
                    if extra and "history" in extra:
                        history = list(extra["history"])
                else:
                    # no checkpoint from this run (a failure before the
                    # first interval save): the failed state's buffers may
                    # already be donated away — rebuild from scratch like a
                    # fresh fit (the keras twin's no-checkpoint branch)
                    variables = model.init(rng, inputs0, **init_kwargs)
                    state = self._place_state(
                        _State.create(apply_fn=model.apply,
                                      params=variables["params"], tx=tx,
                                      batch_stats=variables.get("batch_stats")),
                        state_sharding)
                    epoch = 0
                    history = []

        return state, history

    # ------------------------------------------------------------ partial_fit
    def _partial_fit_epoch(self, ds, epoch: int) -> Dict[str, float]:
        """One online update: a single gradient pass over the epoch's rows
        through the streaming ``DeviceFeed`` (decode/stage/H2D prefetch
        overlap the jitted steps, as in ``fit``). State persists on the
        estimator across epochs; ``self._result`` tracks it so
        ``get_model``/``export_serving`` work mid-stream."""
        import jax
        import time as _time

        from raydp_tpu.data.feed import DeviceFeed

        o = getattr(self, "_online", None)
        if o is None:
            o = self._online_init(ds)
            if o is None:
                # an empty first epoch (a filter matching nothing is
                # routine in streaming) has no schema to init from: report
                # it and keep waiting for rows
                return {"epoch": epoch, "train_loss": float("nan"),
                        "steps": 0, "samples_per_s": 0.0,
                        "epoch_time_s": 0.0, "decode_time_s": 0.0,
                        "h2d_time_s": 0.0}
            self._online = o
        feed = DeviceFeed(ds, self.batch_size, o["columns"], mesh=o["mesh"],
                          shuffle=False, drop_remainder=o["drop_last"],
                          pad_remainder=o["pad_tail"],
                          prefetch_to_device=self.prefetch_to_device,
                          seq=o.get("seq", False))
        t0 = _time.perf_counter()
        mstats = tuple(m.init() for m in self._metrics)
        loss_sum = np.zeros((), np.float32)
        steps = 0
        for batch in feed:
            o["state"], loss_sum, mstats = o["jit_train"](
                o["state"], batch, mstats, loss_sum)
            steps += 1
        train_loss = float(loss_sum) / steps if steps else float("nan")
        dt = _time.perf_counter() - t0
        pipe = feed.timings.take()
        report = {
            "epoch": epoch,
            "train_loss": train_loss,
            "steps": steps,
            "samples_per_s": (steps * self.batch_size / dt) if dt > 0
            else 0.0,
            "epoch_time_s": dt,
            "decode_time_s": pipe.get("decode", 0.0),
            "h2d_time_s": pipe.get("h2d", 0.0),
        }
        for m, s in zip(self._metrics, mstats):
            report[f"train_{m.name}"] = m.compute(
                jax.tree.map(np.asarray, s))
        o["history"].append(report)
        self._result = TrainingResult(state=o["state"],
                                      history=o["history"])
        return report

    def _online_init(self, ds) -> Optional[Dict[str, Any]]:
        """Build the persistent online-training state from the first
        epoch's schema: model/optimizer init, sharded placement, and the
        jitted train step (the same step shape as ``fit``'s, without the
        chaining/device-resident variants — a stream epoch is small).
        None when the epoch holds no rows to init from."""
        import jax
        import jax.numpy as jnp
        from flax.training import train_state

        from raydp_tpu.data.feed import HostBatchIterator
        from raydp_tpu.parallel import param_sharding_rules
        from raydp_tpu.parallel.mesh import batch_sharding, data_axes

        mesh = self._build_mesh()
        columns = self._columns()
        model = self._build_model()
        tx = self._build_optimizer()
        loss_fn = _resolve_loss(self._loss)
        metrics = self._metrics
        first = next(iter(HostBatchIterator(ds, 1, columns, shuffle=False,
                                            drop_remainder=False)), None)
        if first is None:
            return None
        inputs0, _ = self._split_batch(
            {k: jnp.asarray(v[:1]) for k, v in first.items()})
        rng = jax.random.PRNGKey(self.seed)
        takes_train = _takes_train(model)
        init_kwargs = {"train": False} if takes_train else {}
        variables = model.init(rng, inputs0, **init_kwargs)

        class _State(train_state.TrainState):
            batch_stats: Any = None

        state = _State.create(apply_fn=model.apply,
                              params=variables["params"], tx=tx,
                              batch_stats=variables.get("batch_stats"))
        state = self._place_state(
            state, param_sharding_rules(mesh, self.param_rules)(state))

        # the SAME step body as fit()'s (one source): the online path gets
        # gradient accumulation, remat AND pipeline placement for free, and
        # the two cannot drift
        _apply, step_accum, step_remat, accum, n_stages = self._make_forward(
            model, mesh, takes_train, state.params)
        from raydp_tpu import metrics as rdt_metrics
        rdt_metrics.set_gauge("train_accum_steps", accum)
        if isinstance(model, PipelineModel):
            rdt_metrics.set_gauge("train_pipeline_stages", n_stages)
        train_step = _make_train_step(
            _apply, loss_fn, metrics, step_accum, step_remat,
            mb_shardings=(batch_sharding(mesh),
                          batch_sharding(mesh, seq=True)
                          if self._use_seq(mesh) else None))

        dp_total = int(np.prod([mesh.shape[a] for a in data_axes(mesh)]))
        # the ragged micro-batch tail under a >1 data extent (or a >1 stage
        # extent — the pipelined forward cannot reshape a ragged batch):
        # pad-and-mask like fit()'s feeds (an online epoch is often SMALLER
        # than one batch — dropping its tail silently skipped whole
        # micro-batches); RDT_TRAIN_PAD_TAIL=0 or a mask-blind custom loss
        # restores drop
        pad_tail = ((dp_total > 1 or n_stages > 1)
                    and bool(knobs.get("RDT_TRAIN_PAD_TAIL"))
                    and _loss_takes_mask(self._loss))
        return {
            "mesh": mesh,
            "columns": columns,
            "state": state,
            "jit_train": jax.jit(train_step, donate_argnums=(0, 3)),
            "drop_last": (dp_total > 1 or n_stages > 1) and not pad_tail,
            "pad_tail": pad_tail,
            "seq": self._use_seq(mesh),
            "history": [],
        }

    # --------------------------------------------------------------- fit_gang
    def fit_gang(self, train_ds, evaluate_ds=None, *, num_workers: int = 2,
                 max_retries: int = 0, job_name: Optional[str] = None,
                 run_timeout: float = 3600.0,
                 start_timeout: float = 180.0,
                 worker_env: Optional[Dict[str, str]] = None
                 ) -> TrainingResult:
        """Train as a gang of ``num_workers`` processes under one global
        ``jax.distributed`` mesh.

        Parity: ``TorchTrainer`` + ``ScalingConfig(num_workers)`` +
        ``RunConfig(FailureConfig(max_failures))`` (reference
        torch/estimator.py:312-356). Each rank rebuilds the dataset from the
        object store, feeds its slice of every global batch
        (:class:`GangShardIterator` → ``make_array_from_process_local_data``),
        and runs the same jitted train loop; XLA inserts the gradient
        collectives over the global mesh. Parameters may be sharded ACROSS
        processes (fsdp/expert/tensor axes spanning hosts): checkpoints use
        the sharded multi-writer format (each process saves the shards it
        owns, see train/checkpoint.py) and the returned model is assembled
        with a ``process_allgather``.
        A dead or failing rank fails the whole gang (XLA collectives are not
        elastic mid-program, SURVEY.md §7 hard part (c)); the driver then
        restarts the gang, which resumes from the last checkpoint — up to
        ``max_retries`` restarts.

        ``worker_env`` adds/overrides rank-process environment (a ``None``
        value removes the variable) — e.g. pinning ranks to CPU devices on a
        machine whose one TPU chip the driver owns.

        **Shared storage requirement**: on a multi-machine gang,
        ``checkpoint_dir`` must be a filesystem mounted on every rank's host
        (the chief writes step dirs + COMPLETE markers all ranks must see,
        and each rank writes its own parameter shards there). The default —
        a driver-local temp dir — only works when all ranks share the
        driver's machine; ranks that cannot see the directory fail fast at
        startup with a clear error.
        """
        import copy
        import uuid as _uuid

        from raydp_tpu.spmd.job import create_spmd_job

        if self._mesh is not None:
            raise ValueError("fit_gang builds its mesh inside the ranks; "
                             "pass mesh_spec instead of a driver-built mesh")
        ckpt_dir = self.checkpoint_dir or tempfile.mkdtemp(prefix="rdt-gang-")
        if self.checkpoint_dir:
            # gang ranks run with resume=True by design (the restart loop
            # below depends on it), so THIS is the one path where a fresh fit
            # pointed at a reused dir silently ADOPTS the earlier run's
            # latest step — warn before the ranks start
            from raydp_tpu.train.checkpoint import warn_if_reused_dir
            warn_if_reused_dir(ckpt_dir)
        train_payload = train_ds.portable()
        eval_payload = evaluate_ds.portable() if evaluate_ds is not None else None

        est = copy.copy(self)
        est._result = None
        est.checkpoint_dir = ckpt_dir

        def _rank_fit(ctx):
            return est._gang_rank_fit(ctx, train_payload, eval_payload,
                                      ckpt_dir)

        job = create_spmd_job(job_name or f"flaxfit-{_uuid.uuid4().hex[:6]}",
                              num_workers, jax_distributed=True,
                              env=worker_env, timeout=start_timeout)
        attempts = 0
        while True:
            try:
                job.start()
                results = job.run(_rank_fit, timeout=run_timeout)
                job.stop()
                break
            except (KeyboardInterrupt, SystemExit):
                job.stop()
                raise
            except Exception as e:  # noqa: BLE001 - gang restart (FailureConfig)
                job.stop()
                attempts += 1
                if attempts > max_retries:
                    raise
                logger.warning("gang fit failed (%s); restarting gang from "
                               "last checkpoint (retry %d/%d)",
                               e, attempts, max_retries)

        chief = results[0]
        from types import SimpleNamespace
        state = SimpleNamespace(
            params=chief["model_vars"]["params"],
            batch_stats=chief["model_vars"].get("batch_stats"))
        self._result = TrainingResult(state=state, history=chief["history"],
                                      checkpoint_dir=ckpt_dir)
        return self._result

    def _gang_rank_fit(self, ctx, train_payload, eval_payload, ckpt_dir: str):
        """Runs inside each SPMD rank (the reference's ``train_func`` body,
        torch/estimator.py:177-310)."""
        import jax

        from raydp_tpu.data.dataset import DistributedDataset
        from raydp_tpu.data.feed import DeviceFeed, GangShardIterator

        columns = self._columns()
        mesh = self._build_mesh()  # jax.devices() is global under the gang
        # sharded multi-writer checkpoints assume ONE filesystem: the chief
        # mkdirs each step dir and its COMPLETE marker must be visible to
        # every rank on resume — fail fast on per-host paths, don't deadlock
        from raydp_tpu.train.checkpoint import ensure_shared_dir
        ensure_shared_dir(ckpt_dir, "rdt_ckpt_dir_probe")
        from raydp_tpu.data.feed import process_local_batch_rows
        from raydp_tpu.parallel import batch_sharding

        # this process's addressable slice of each global batch, derived from
        # the actual batch sharding: with the batch replicated over a size-1
        # data axis (e.g. pure fsdp/expert meshes) EVERY process feeds the
        # full batch; with a >1 data axis each feeds its contiguous rows
        row_range = process_local_batch_rows(batch_sharding(mesh),
                                             self.batch_size)
        train_ds = DistributedDataset.from_portable(train_payload)
        feed = DeviceFeed(
            train_ds, self.batch_size, columns, mesh=mesh,
            prefetch_to_device=self.prefetch_to_device,
            seq=self._use_seq(mesh),
            host_iter=GangShardIterator(
                train_ds, self.batch_size, ctx.world_size, ctx.rank, columns,
                shuffle=self.shuffle, seed=self.seed, row_range=row_range))
        eval_feed = None
        if eval_payload is not None:
            eval_ds = DistributedDataset.from_portable(eval_payload)
            eval_feed = DeviceFeed(
                eval_ds, self.batch_size, columns, mesh=mesh,
                prefetch_to_device=self.prefetch_to_device,
                seq=self._use_seq(mesh),
                host_iter=GangShardIterator(
                    eval_ds, self.batch_size, ctx.world_size, ctx.rank,
                    columns, shuffle=False, seed=self.seed,
                    row_range=row_range))

        state, history = self._train_loop(mesh, feed, eval_feed, ckpt_dir,
                                          max_retries=0, resume=True)
        out = {"history": history}
        # collect the trained variables on every host (collective — all ranks
        # participate), then rank 0 returns them; with params sharded across
        # processes this is the only way any single process sees full values
        from jax.experimental import multihost_utils

        model_vars = {"params": state.params}
        bstats = getattr(state, "batch_stats", None)
        if bstats is not None:
            model_vars["batch_stats"] = bstats
        host_vars = jax.tree.map(
            np.asarray, multihost_utils.process_allgather(model_vars,
                                                          tiled=True))
        if ctx.rank == 0:
            out["model_vars"] = host_vars
        return out

    # ----------------------------------------------------------- fit_on_frame
    def fit_on_frame(self, train_df, evaluate_df=None, *,
                     fs_directory: Optional[str] = None,
                     stop_etl_after_conversion: bool = False,
                     max_retries: int = 0,
                     num_workers: Optional[int] = None) -> TrainingResult:
        train_ds, eval_ds = self._convert_frames(
            train_df, evaluate_df, fs_directory=fs_directory,
            stop_etl_after_conversion=stop_etl_after_conversion)

        gang = num_workers is not None and num_workers > 1
        if self.shuffle:
            # parity: random_shuffle before training (torch/estimator.py:335-338)
            # — except on the single-process device-resident path, whose
            # on-device per-epoch permutation IS a uniform row shuffle: the
            # extra O(dataset) pass through the object store buys nothing
            from raydp_tpu.data.feed import DeviceEpochCache
            resident = not gang and DeviceEpochCache.eligible(
                train_ds, self._columns(), self.batch_size, self.drop_last)
            if not resident:
                train_ds = train_ds.random_shuffle(seed=self.seed)
        if gang:
            return self.fit_gang(train_ds, eval_ds, num_workers=num_workers,
                                 max_retries=max_retries)
        return self.fit(train_ds, eval_ds, max_retries=max_retries)

    # ---------------------------------------------------------------- predict
    def predict(self, ds, batch_size: Optional[int] = None) -> np.ndarray:
        """Run the trained model over a dataset and return predictions as
        one host array (row order = dataset block order).

        Convenience beyond the reference (whose users rebuild an inference
        loop around ``get_model``). Works for plain ``feature_columns``
        models AND for ``batch_preprocessor`` / ``columns_spec`` models
        (e.g. DLRM): those decode the same column spec the train feed used
        and run the preprocessor in-jit per batch, exactly like the train
        step. ANY spec entry whose column(s) the dataset lacks (the normal
        inference frame's label — whatever the entry is keyed, a
        preprocessor may name it anything) is synthesized as zeros — the
        preprocessor's label output is discarded anyway.
        """
        import jax
        import jax.numpy as jnp

        from raydp_tpu.data.feed import HostBatchIterator

        model = self._build_model()
        variables = self.get_model()   # raises if fit() has not run
        kwargs = {"train": False} if _takes_train(model) else {}

        compute_dtype = self.compute_dtype
        custom = (self.batch_preprocessor is not None
                  or self.columns_spec is not None)
        split_batch = self._split_batch

        @jax.jit
        def infer(jbatch):
            # preprocessor + cast run INSIDE jit, like the train step's
            # _apply — one dispatch per batch, no eager slicing/casting
            inputs = split_batch(jbatch)[0] if custom \
                else jbatch["features"]
            inputs = _cast_floating(inputs, compute_dtype)
            preds = model.apply(variables, inputs, **kwargs)
            if preds.ndim >= 2 and preds.shape[-1] == 1:
                preds = preds.squeeze(-1)
            return preds.astype(jnp.float32)

        cols = dict(self._columns()) if custom else {
            "features": (self.feature_columns, self.feature_dtype)}
        synth: Dict[str, Tuple[Tuple[str, ...], np.dtype]] = {}
        if custom:
            have = set(ds.schema.names)
            for name, (cspec, dt) in list(cols.items()):
                cnames = (cspec,) if isinstance(cspec, str) else tuple(cspec)
                missing = [c for c in cnames if c not in have]
                if missing and len(missing) < len(cnames):
                    # some of the entry's columns exist and some don't: that
                    # is a schema mismatch (renamed/dropped feature), not a
                    # label-less inference frame — zero-filling half a
                    # feature matrix would silently predict garbage
                    raise ValueError(
                        f"columns_spec entry {name!r} is partially missing "
                        f"from the dataset schema: missing {missing}")
                if missing:
                    # the entry is absent wholesale (the usual case: a label
                    # column inference data never carries, under whatever key
                    # the spec chose) — synthesize it as zeros
                    cols.pop(name)
                    synth[name] = (cnames, np.dtype(dt))
                    logger.info("predict: columns_spec entry %r absent from "
                                "the dataset schema; synthesizing zeros",
                                name)
            if not cols:
                raise ValueError(
                    "no columns_spec entry matches the dataset schema "
                    f"{sorted(have)}; cannot synthesize every input")
        it = HostBatchIterator(ds, batch_size or self.batch_size, cols,
                               shuffle=False, drop_remainder=False)
        out = []
        for batch in it:
            rows = len(next(iter(batch.values())))
            for name, (cnames, dt) in synth.items():
                # match the decoded shape contract of _as_numpy: one column
                # decodes to [rows], several to [rows, n]
                shape = (rows,) if len(cnames) == 1 else (rows, len(cnames))
                batch[name] = np.zeros(shape, dt)
            out.append(np.asarray(infer(
                {k: jnp.asarray(v) for k, v in batch.items()})))
        if not out:
            return np.empty((0,), np.float32)
        return np.concatenate(out, axis=0)

    # --------------------------------------------------------- export_serving
    def export_serving(self, export_dir: str) -> str:
        """Write a serving bundle for :class:`raydp_tpu.serve.ServingSession`:
        the trained variables through ``train/checkpoint.py`` plus the
        pickled inference recipe (model, column spec, preprocessor, cast
        policy) — exactly what :meth:`predict` uses, so a replica's output
        is row-identical to a driver-side ``predict()`` on the same rows.
        Multi-host executor pools need ``export_dir`` on shared storage (the
        gang-checkpoint contract)."""
        from raydp_tpu.serve.servable import export_bundle

        model = self._build_model()
        variables = self.get_model()   # raises if fit() has not run
        custom = (self.batch_preprocessor is not None
                  or self.columns_spec is not None)
        # non-custom models consume only "features"; the custom path ships
        # the full spec and the replica synthesizes absent entries (the
        # label) as zeros, like predict()
        columns = (dict(self._columns()) if custom
                   else {"features": (self.feature_columns,
                                      self.feature_dtype)})
        bundle = {
            "model": model,
            "columns": columns,
            "custom": custom,
            "preprocessor": self.batch_preprocessor,
            "compute_dtype": self.compute_dtype,
            "takes_train": _takes_train(model),
        }
        return export_bundle(export_dir, "flax", bundle, variables)

    # -------------------------------------------------------------- get_model
    def get_model(self):
        """Trained Flax variables (parity: get_model from checkpoint,
        torch/estimator.py:392-396)."""
        if self._result is None:
            raise RuntimeError("call fit()/fit_on_frame() first")
        out = {"params": self._result.state.params}
        bstats = getattr(self._result.state, "batch_stats", None)
        if bstats is not None:
            out["batch_stats"] = bstats
        return out

    def get_state(self):
        if self._result is None:
            raise RuntimeError("call fit()/fit_on_frame() first")
        return self._result.state
