"""GBDTEstimator: the XGBoostEstimator-parity trainer, XLA-native trees.

Parity map (reference xgboost/estimator.py):

- ``XGBoostEstimator(params, label_column, num_boost_round)`` thin wrapper over
  ``ray.train.xgboost.XGBoostTrainer`` (54-81) — here the same sklearn shape
  over :func:`raydp_tpu.models.gbdt.fit_gbdt`, whose histogram scatter-adds
  are where XGBoost's Rabit allreduce sits (the data-parallel plug point).
- per-iteration ``CheckpointConfig(num_to_keep=1)`` (60-68) — the forest's
  split/leaf tables are snapshotted per fit and saved to ``checkpoint_dir``.
- ``fit_on_spark`` conversion paths + ``get_model`` (83-119) —
  ``fit_on_frame`` / ``get_model`` below.

Accepted ``params`` keys follow xgboost naming: ``objective``
(``reg:squarederror`` | ``binary:logistic`` | ``multi:softmax`` |
``multi:softprob``), ``num_class``, ``max_depth``, ``eta`` /
``learning_rate``, ``lambda`` / ``reg_lambda``, ``min_child_weight``,
``max_bin``. Eval sets are scored every boosting round
(``result.evals_result``, parity: xgboost per-round eval reporting) and
``early_stopping_rounds`` stops and truncates to the best iteration;
``weight_column`` supplies per-row instance weights.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Dict, List, Optional, Sequence

import numpy as np

from raydp_tpu.log import get_logger
from raydp_tpu.train.estimator import EstimatorInterface, FrameEstimatorInterface
from raydp_tpu.train.flax_estimator import TrainingResult

logger = get_logger("train.gbdt_estimator")


class GBDTEstimator(EstimatorInterface, FrameEstimatorInterface):
    def __init__(
        self,
        params: Optional[Dict] = None,
        feature_columns: Optional[Sequence[str]] = None,
        label_column: Optional[str] = None,
        num_boost_round: int = 100,
        checkpoint_dir: Optional[str] = None,
        early_stopping_rounds: Optional[int] = None,
        weight_column: Optional[str] = None,
        mesh=None,
    ):
        params = dict(params or {})
        self.objective = params.pop("objective", "reg:squarederror")
        self.num_class = params.pop("num_class", None)
        self.max_depth = int(params.pop("max_depth", 6))
        self.learning_rate = float(params.pop(
            "eta", params.pop("learning_rate", 0.3)))
        self.reg_lambda = float(params.pop(
            "lambda", params.pop("reg_lambda", 1.0)))
        self.min_child_weight = float(params.pop("min_child_weight", 1.0))
        self.num_bins = int(params.pop("max_bin", 256))
        if "early_stopping_rounds" in params:
            early_stopping_rounds = params.pop("early_stopping_rounds")
        if params:
            logger.warning("ignoring unsupported params: %s", sorted(params))
        self.feature_columns = list(feature_columns or [])
        self.label_column = label_column
        self.num_boost_round = num_boost_round
        self.checkpoint_dir = checkpoint_dir
        self.early_stopping_rounds = early_stopping_rounds
        self.weight_column = weight_column
        self.mesh = mesh  # rows sharded over its data axes (distributed trees)
        self._model = None
        self._result: Optional[TrainingResult] = None
        self.evals_result: Dict = {}

    # ------------------------------------------------------------------ data
    def _feature_matrix(self, table) -> np.ndarray:
        return np.stack([table.column(c).to_numpy(zero_copy_only=False)
                         .astype(np.float32, copy=False)
                         for c in self.feature_columns], axis=1)

    def _materialize(self, ds, with_weight: bool = False):
        if ds is None:
            return None
        if not self.feature_columns or self.label_column is None:
            raise ValueError("pass feature_columns and label_column")
        table = ds.to_arrow()
        X = self._feature_matrix(table)
        y = (table.column(self.label_column).to_numpy(zero_copy_only=False)
             .astype(np.float32, copy=False))
        if with_weight and self.weight_column is not None:
            w = (table.column(self.weight_column)
                 .to_numpy(zero_copy_only=False).astype(np.float32, copy=False))
            return X, y, w
        return (X, y, None) if with_weight else (X, y)

    def _metrics_from_margin(self, margin, y, prefix: str) -> Dict[str, float]:
        from raydp_tpu.models.gbdt import eval_metric

        name, value = eval_metric(margin, y, self.objective)
        out = {f"{prefix}_{name}": value}
        if self.objective == "binary:logistic":
            p = 1.0 / (1.0 + np.exp(-margin))
            out[f"{prefix}_error"] = float(((p > 0.5) != (y > 0.5)).mean())
        elif self.objective.startswith("multi:"):
            out[f"{prefix}_merror"] = float(
                (margin.argmax(axis=1) != y.astype(np.int64)).mean())
        return out

    # ------------------------------------------------------------------- fit
    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0
            ) -> TrainingResult:
        from raydp_tpu.models.gbdt import fit_gbdt

        X, y, w = self._materialize(train_ds, with_weight=True)
        evals = self._materialize(evaluate_ds)

        model, train_margin, evals_result = fit_gbdt(
            X, y, num_trees=self.num_boost_round, max_depth=self.max_depth,
            num_bins=self.num_bins, learning_rate=self.learning_rate,
            reg_lambda=self.reg_lambda, min_child_weight=self.min_child_weight,
            objective=self.objective, num_class=self.num_class,
            sample_weight=w, evals=evals,
            early_stopping_rounds=self.early_stopping_rounds,
            mesh=self.mesh)
        self.evals_result = evals_result

        report = {"num_trees": model.num_trees}
        if model.best_iteration is not None:
            report["best_iteration"] = model.best_iteration
        report.update(self._metrics_from_margin(train_margin, y, "train"))
        if evals is not None:
            eX, ey = evals
            report.update(self._metrics_from_margin(
                model.predict(eX, output_margin=True), ey, "eval"))
        logger.info("gbdt fit: %s", report)

        ckpt_dir = self.checkpoint_dir or tempfile.mkdtemp(prefix="rdt-gbdt-")
        os.makedirs(ckpt_dir, exist_ok=True)
        with open(os.path.join(ckpt_dir, "model.pkl"), "wb") as fh:
            pickle.dump(model, fh)

        self._model = model
        self._result = TrainingResult(state=model, history=[report],
                                      checkpoint_dir=ckpt_dir)
        return self._result

    # ---------------------------------------------------------- fit_on_frame
    def fit_on_frame(self, train_df, evaluate_df=None, *,
                     fs_directory: Optional[str] = None,
                     stop_etl_after_conversion: bool = False,
                     max_retries: int = 0) -> TrainingResult:
        train_ds, eval_ds = self._convert_frames(
            train_df, evaluate_df, fs_directory=fs_directory,
            stop_etl_after_conversion=stop_etl_after_conversion)
        return self.fit(train_ds, eval_ds, max_retries=max_retries)

    # ------------------------------------------------------------- get_model
    def get_model(self):
        """The fitted :class:`~raydp_tpu.models.gbdt.GBDTModel`
        (parity: xgboost/estimator.py:110-119)."""
        if self._model is None:
            raise RuntimeError("call fit()/fit_on_frame() first")
        return self._model

    def predict(self, ds, output_margin: bool = False) -> np.ndarray:
        """Run the fitted trees over a dataset's feature columns (the same
        convenience FlaxEstimator.predict adds beyond the reference, whose
        users rebuild an inference loop around ``get_model``)."""
        model = self.get_model()
        X = self._feature_matrix(ds.to_arrow())
        return model.predict(X, output_margin=output_margin)

    @staticmethod
    def load_model(checkpoint_dir: str):
        with open(os.path.join(checkpoint_dir, "model.pkl"), "rb") as fh:
            return pickle.load(fh)
