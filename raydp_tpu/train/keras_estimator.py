"""KerasEstimator: the TFEstimator-parity trainer on Keras 3's JAX backend.

Parity map (reference tf/estimator.py):

- the estimator owns a serialized model *spec*, not a live object — the
  reference serializes the model to JSON and optimizer/loss/metrics through
  keras serialize (96-149) so they rebuild inside workers; here
  ``keras.saving.serialize_keras_object`` round-trips them the same way.
- ``train_func`` opens a ``tf.distribute.MultiWorkerMirroredStrategy`` scope →
  compile → ``to_tf`` dataset → ``model.fit`` (171-210); here the default
  training path is a **jitted stateless loop** over the device mesh — Keras 3's
  functional API (``model.stateless_call`` / ``optimizer.stateless_apply`` /
  stateless metrics) inside ONE ``jax.jit`` step with donated buffers, fed by
  the same :class:`~raydp_tpu.data.feed.DeviceFeed` streaming/prefetching
  pipeline the FlaxEstimator uses. That removes ``model.fit``'s per-batch
  Python dispatch (the 14× gap of round 2); collectives are XLA collectives
  over ICI, no TF runtime involved. Exotic ``fit_kwargs`` fall back to the
  stock ``model.fit`` path.
- ``fit_gang`` trains as a multi-process gang under ``jax.distributed`` —
  each rank feeds its shard of every global batch, parameters replicate, XLA
  inserts the gradient collectives (the MWMS-across-hosts analogue).
- ``merge_feature_columns`` via ray.data ``Concatenator`` (237-260) — the host
  feed stacks feature columns into one matrix the same way.
- chief-only checkpoint (202-210) — process-0 saves ``model.keras`` per epoch.
- same ``fit`` / ``fit_on_spark`` / ``get_model`` surface (212-310) —
  ``fit`` / ``fit_on_frame`` / ``get_model`` below.

Keras must run on the JAX backend; this module asserts it (the reference
equally hard-requires TF inside its workers).
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from raydp_tpu.log import get_logger
from raydp_tpu.train.estimator import (
    EstimatorInterface,
    FrameEstimatorInterface,
    save_epoch_now,
)
from raydp_tpu.train.flax_estimator import TrainingResult

logger = get_logger("train.keras_estimator")

os.environ.setdefault("KERAS_BACKEND", "jax")


def _import_keras():
    import keras

    if keras.backend.backend() != "jax":
        raise RuntimeError(
            "raydp_tpu.KerasEstimator requires the JAX backend; set "
            "KERAS_BACKEND=jax before the first keras import "
            f"(found {keras.backend.backend()!r})")
    return keras


class KerasEstimator(EstimatorInterface, FrameEstimatorInterface):
    """sklearn-style estimator for Keras models, SPMD over the device mesh."""

    def __init__(
        self,
        model=None,
        model_builder: Optional[Callable] = None,
        optimizer="adam",
        loss: Union[str, Callable] = "mse",
        metrics: Optional[Sequence] = None,
        feature_columns: Optional[Sequence[str]] = None,
        label_column: Optional[str] = None,
        batch_size: int = 64,
        num_epochs: int = 10,
        shuffle: bool = True,
        data_parallel: bool = True,
        checkpoint_dir: Optional[str] = None,
        seed: int = 0,
        feature_dtype=np.float32,
        label_dtype=np.float32,
        drop_last: bool = True,
        fit_kwargs: Optional[Dict] = None,
        steps_per_dispatch: int = 1,
        checkpoint_interval: int = 1,
        prefetch_to_device: Optional[int] = None,
    ):
        keras = _import_keras()
        if model is None and model_builder is None:
            raise ValueError("pass model or model_builder")
        # serialize the spec so fit() rebuilds fresh objects each run
        # (parity: tf/estimator.py:96-149 JSON/keras-serialize round-trip)
        self._model_spec = (keras.saving.serialize_keras_object(model)
                            if model is not None else None)
        self._model_builder = model_builder
        self._optimizer_spec = keras.saving.serialize_keras_object(
            keras.optimizers.get(optimizer))
        self._loss = loss
        self._metrics = list(metrics or [])
        self.feature_columns = list(feature_columns or [])
        self.label_column = label_column
        self.batch_size = batch_size
        self.num_epochs = num_epochs
        self.shuffle = shuffle
        self.data_parallel = data_parallel
        self.checkpoint_dir = checkpoint_dir
        self.seed = seed
        self.feature_dtype = feature_dtype
        self.label_dtype = label_dtype
        self.drop_last = drop_last
        self.fit_kwargs = dict(fit_kwargs or {})
        #: chain k train steps per jitted dispatch (lax.scan over a stacked
        #: batch) — k× fewer host→device round trips, numerically identical
        #: (see FlaxEstimator.steps_per_dispatch)
        self.steps_per_dispatch = max(1, int(steps_per_dispatch))
        #: checkpoint every N-th epoch, final epoch always (see the flax
        #: twin; model.save of a keras archive can outweigh a resident epoch)
        self.checkpoint_interval = max(1, int(checkpoint_interval))
        #: device-placed batches the streaming feed keeps ahead of the train
        #: step (None = the feed default / RDT_PREFETCH_TO_DEVICE, 2) — see
        #: the flax twin; bit-identical to synchronous placement
        self.prefetch_to_device = prefetch_to_device
        self._trained_model = None
        self._result: Optional[TrainingResult] = None

    # ------------------------------------------------------------------ build
    def _build_model(self):
        keras = _import_keras()
        if self._model_spec is not None:
            return keras.saving.deserialize_keras_object(self._model_spec)
        return self._model_builder()

    def _maybe_distribute(self):
        """DataParallel over all local devices when >1 (the MWMS-scope
        analogue, tf/estimator.py:173-176). Returns the caller's previous
        distribution so ``fit`` can restore it."""
        keras = _import_keras()
        previous = keras.distribution.distribution()
        import jax
        if self.data_parallel and len(jax.devices()) > 1:
            keras.distribution.set_distribution(
                keras.distribution.DataParallel())
        return previous

    def _materialize(self, ds):
        """Dataset → (features [n, d], labels [n]) host arrays.

        Feature columns merge into one contiguous matrix (parity:
        ``merge_feature_columns`` Concatenator, tf/estimator.py:237-260)."""
        if ds is None:
            return None
        if not self.feature_columns or self.label_column is None:
            raise ValueError("pass feature_columns and label_column")
        table = ds.to_arrow()
        feats = np.stack(
            [table.column(c).to_numpy(zero_copy_only=False)
             .astype(self.feature_dtype, copy=False)
             for c in self.feature_columns], axis=1)
        labels = (table.column(self.label_column)
                  .to_numpy(zero_copy_only=False)
                  .astype(self.label_dtype, copy=False))
        return feats, labels

    def _trim(self, arrays, n_devices: int):
        """Static shapes under data parallelism: drop the ragged tail so every
        batch splits evenly over devices (same reason the DeviceFeed drops
        remainders — a changing batch dim retraces under jit)."""
        feats, labels = arrays
        if not self.drop_last:
            return feats, labels
        step = self.batch_size
        n = (len(feats) // step) * step
        if n == 0:
            n = (len(feats) // n_devices) * n_devices
        return (feats[:n], labels[:n]) if n else (feats, labels)

    # -------------------------------------------------------------------- fit
    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0
            ) -> TrainingResult:
        """Train. Default: the jitted stateless loop (fast path). Any custom
        ``fit_kwargs`` (validation_split, class_weight, ...) fall back to
        stock ``model.fit`` semantics."""
        if not self.fit_kwargs:
            return self._fit_stateless(train_ds, evaluate_ds,
                                       max_retries=max_retries)
        return self._fit_keras_loop(train_ds, evaluate_ds,
                                    max_retries=max_retries)

    # ---------------------------------------------------- stateless fast path
    def _columns(self) -> Dict:
        if not self.feature_columns or self.label_column is None:
            raise ValueError("pass feature_columns and label_column")
        return {
            "features": (list(self.feature_columns), self.feature_dtype),
            "label": (self.label_column, self.label_dtype),
        }

    def _mesh(self):
        import jax

        from raydp_tpu.parallel import make_mesh
        devices = jax.devices() if self.data_parallel else jax.devices()[:1]
        return make_mesh(devices=devices)

    def _fit_stateless(self, train_ds, evaluate_ds=None, max_retries: int = 0
                       ) -> TrainingResult:
        import numpy as _np

        from raydp_tpu.data.feed import DeviceFeed
        from raydp_tpu.parallel.mesh import data_axes

        mesh = self._mesh()
        columns = self._columns()
        ckpt_dir = self.checkpoint_dir or tempfile.mkdtemp(
            prefix="rdt-keras-ckpt-")
        os.makedirs(ckpt_dir, exist_ok=True)
        # device-resident fast path (see feed.DeviceEpochCache): whole epoch
        # in one dispatch, on-device shuffling — streaming feed otherwise
        from raydp_tpu.data.feed import DeviceEpochCache
        cache = feed = None
        if DeviceEpochCache.eligible(train_ds, columns, self.batch_size,
                                     self.drop_last):
            cache = DeviceEpochCache(train_ds, columns, mesh=mesh)
        if cache is None:
            feed = DeviceFeed(train_ds, self.batch_size, columns, mesh=mesh,
                              shuffle=self.shuffle, seed=self.seed,
                              drop_remainder=self.drop_last,
                              prefetch_to_device=self.prefetch_to_device)
        eval_feed = eval_cache = None
        if evaluate_ds is not None:
            dp_total = int(_np.prod([mesh.shape[a] for a in data_axes(mesh)]))
            # resident eval beside resident train: one scan dispatch per
            # eval pass, under a COMBINED train+eval budget (see flax twin)
            if (cache is not None
                    and DeviceEpochCache.eligible(evaluate_ds, columns,
                                                  1, True)
                    and cache.nbytes + DeviceEpochCache.estimate_bytes(
                        evaluate_ds, columns) <= DeviceEpochCache.cap_bytes()):
                eval_cache = DeviceEpochCache(evaluate_ds, columns, mesh=mesh)
            else:
                eval_feed = DeviceFeed(evaluate_ds, self.batch_size, columns,
                                       mesh=mesh, shuffle=False,
                                       drop_remainder=dp_total > 1,
                                       prefetch_to_device=self.prefetch_to_device)
        model, history = self._stateless_train_loop(
            mesh, feed, eval_feed, ckpt_dir, max_retries=max_retries,
            cache=cache, eval_cache=eval_cache,
            eval_tail_ok=evaluate_ds is not None and dp_total == 1)
        self._trained_model = model
        self._result = TrainingResult(state=model, history=history,
                                      checkpoint_dir=ckpt_dir)
        return self._result

    def _metric_objects(self):
        """Fresh metric instances (spec round-trip so repeated fits and rank
        processes never share stateful metric objects). ``"accuracy"`` is
        resolved against the loss the way ``model.compile`` does — the bare
        ``Accuracy`` metric is exact-match and reads ~0 on probabilities."""
        keras = _import_keras()
        loss_name = (self._loss if isinstance(self._loss, str)
                     else getattr(self._loss, "name", ""))
        out = []
        for m in self._metrics:
            if isinstance(m, str) and m in ("accuracy", "acc"):
                if "binary" in loss_name:
                    out.append(keras.metrics.BinaryAccuracy(name="accuracy"))
                elif "sparse_categorical" in loss_name:
                    out.append(keras.metrics.SparseCategoricalAccuracy(
                        name="accuracy"))
                elif "categorical" in loss_name:
                    out.append(keras.metrics.CategoricalAccuracy(
                        name="accuracy"))
                else:
                    out.append(keras.metrics.get(m))
            elif isinstance(m, str):
                out.append(keras.metrics.get(m))
            else:
                out.append(keras.saving.deserialize_keras_object(
                    keras.saving.serialize_keras_object(m)))
        return out

    def _stateless_train_loop(self, mesh, feed, eval_feed, ckpt_dir: str,
                              max_retries: int = 0, resume: bool = False,
                              cache=None, eval_cache=None,
                              eval_tail_ok: bool = False):
        """One jitted train step over stateless Keras calls; in-jit loss and
        metric accumulation; donated state buffers; chief-only per-epoch
        ``model.keras`` checkpoint with a JSON epoch/history sidecar.

        Parity: the role ``model.fit`` under an MWMS scope plays for the
        reference (tf/estimator.py:171-210) — redesigned as an XLA-compiled
        step because per-batch Python dispatch is what made the round-2 Keras
        path 14× slower than the Flax path on the same chip."""
        import json as _json
        import time as _time

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        keras = _import_keras()

        keras.utils.set_random_seed(self.seed)
        model = self._build_model()
        optimizer = keras.saving.deserialize_keras_object(self._optimizer_spec)
        loss_obj = keras.losses.get(self._loss)
        train_metrics = self._metric_objects()
        eval_metrics = self._metric_objects()

        saved_model = os.path.join(ckpt_dir, "model.keras")
        saved_meta = os.path.join(ckpt_dir, "state.json")
        saved_opt = os.path.join(ckpt_dir, "optimizer.npz")

        def _ckpt_available():
            return (os.path.exists(saved_model)
                    and os.path.exists(saved_meta))

        history: list = []
        epoch0 = 0
        restored = False
        if not resume and self.checkpoint_dir and _ckpt_available():
            # the flax twin's reused-dir warning (checkpoint.warn_if_reused_dir)
            # for the keras model.keras/state.json format: this fit will
            # overwrite, but the user should learn the dir held an earlier
            # run before a later resume silently adopts whichever run wrote
            # last
            logger.warning(
                "checkpoint_dir %r already holds a model.keras/state.json "
                "from an earlier run; this fit overwrites them — use a fresh "
                "checkpoint_dir per run to keep runs separate", ckpt_dir)
        if resume:
            # gang: all ranks must resume the SAME epoch or their collective
            # counts diverge and the first psum deadlocks — take the CHIEF's
            # view of the sidecar (lagging visibility on networked storage
            # can make ranks disagree), exactly like checkpoint._latest_agreed
            local_epoch = -1
            if _ckpt_available():
                with open(saved_meta) as f:
                    meta = _json.load(f)
                local_epoch = int(meta["epoch"])
            chief_epoch = local_epoch
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                import numpy as _np
                chief_epoch = int(multihost_utils.broadcast_one_to_all(
                    _np.int32(local_epoch)))
            if chief_epoch >= 0:
                if not _ckpt_available():
                    raise FileNotFoundError(
                        f"chief resumes keras checkpoint epoch {chief_epoch} "
                        f"but this rank cannot see {ckpt_dir!r}; gangs need "
                        "shared checkpoint storage")
                model = keras.saving.load_model(saved_model)
                with open(saved_meta) as f:
                    meta = _json.load(f)
                epoch0 = chief_epoch + 1
                history = list(meta["history"])[:chief_epoch + 1]
                restored = True
                logger.info("keras resuming from checkpoint epoch %d",
                            chief_epoch)

        # build weights + optimizer slots from one sample batch's shapes
        first = cache.init_row if cache is not None \
            else next(iter(feed.host_iter))
        if not model.built:
            model.build(first["features"][:1].shape)
        optimizer.build(model.trainable_variables)

        rep = NamedSharding(mesh, PartitionSpec())

        def _place(values):
            return [jax.device_put(jnp.asarray(v), rep) for v in values]

        def _restore_opt():
            """Optimizer slots (Adam moments, iteration) from the sidecar —
            resuming with zeroed slots would silently diverge from an
            uninterrupted run (the FlaxEstimator checkpoints its full
            TrainState; this is the keras-format equivalent). Gang ranks take
            the chief's slot values like the weights."""
            vals = None
            if os.path.exists(saved_opt):
                with np.load(saved_opt) as z:
                    vals = [z[f"v{i}"] for i in range(len(z.files))]
                if len(vals) != len(optimizer.variables):
                    logger.warning("optimizer sidecar has %d slots, expected "
                                   "%d; starting slots fresh", len(vals),
                                   len(optimizer.variables))
                    vals = None
            if vals is None:
                vals = [np.asarray(v.value) for v in optimizer.variables]
            return _place(_chief_sync(vals))

        def _chief_sync(values):
            """On a restored gang, every rank takes the CHIEF's host values —
            a rank that read a staler file version must not train different
            weights (the collective math would silently diverge)."""
            if not (restored and jax.process_count() > 1):
                return values
            from jax.experimental import multihost_utils
            return multihost_utils.broadcast_one_to_all(
                [np.asarray(v) for v in values])

        tv = _place(_chief_sync([v.value for v in model.trainable_variables]))
        ntv = _place(_chief_sync(
            [v.value for v in model.non_trainable_variables]))
        ov = _restore_opt() if restored \
            else _place([v.value for v in optimizer.variables])

        # initial metric states snapshotted to HOST: the per-epoch device
        # copies are donated into the jitted steps, so re-reading the keras
        # variables' (consumed) buffers next epoch would use deleted arrays
        tm_init = tuple(tuple(np.asarray(v.value) for v in m.variables)
                        for m in train_metrics)
        em_init = tuple(tuple(np.asarray(v.value) for v in m.variables)
                        for m in eval_metrics)

        def _mvars(init):
            return tuple(tuple(jnp.asarray(v) for v in t) for t in init)

        def _match_rank(y, preds):
            if y.ndim == preds.ndim - 1 and preds.shape[-1] == 1:
                return y[..., None]
            return y

        def _loss_and_updates(tv, ntv, x, y):
            preds, ntv2 = model.stateless_call(tv, ntv, x, training=True)
            y2 = _match_rank(y, preds)
            # keras.losses.get("mse") yields the per-sample FUNCTION; Loss
            # instances already reduce — jnp.mean covers both
            loss = jnp.mean(loss_obj(y2, preds))
            return loss, (preds, y2, ntv2)

        grad_fn = jax.value_and_grad(_loss_and_updates, has_aux=True)

        def train_step(tv, ntv, ov, mvars, loss_sum, batch):
            x, y = batch["features"], batch["label"]
            (loss, (preds, y2, ntv2)), grads = grad_fn(tv, ntv, x, y)
            tv2, ov2 = optimizer.stateless_apply(ov, grads, tv)
            mvars2 = tuple(
                tuple(m.stateless_update_state(list(mv), y2, preds))
                for m, mv in zip(train_metrics, mvars))
            return tv2, ntv2, ov2, mvars2, loss_sum + loss

        def eval_step(tv, ntv, mvars, loss_sum, batch):
            x, y = batch["features"], batch["label"]
            preds, _ = model.stateless_call(tv, ntv, x, training=False)
            y2 = _match_rank(y, preds)
            loss = jnp.mean(loss_obj(y2, preds))
            mvars2 = tuple(
                tuple(m.stateless_update_state(list(mv), y2, preds))
                for m, mv in zip(eval_metrics, mvars))
            return mvars2, loss_sum + loss * y.shape[0]

        jit_train = jax.jit(train_step, donate_argnums=(0, 1, 2, 3, 4))
        jit_eval = jax.jit(eval_step, donate_argnums=(2, 3))

        chain = self.steps_per_dispatch
        jit_chain = None
        if chain > 1 and cache is None:
            from jax import lax

            def train_chain(tv, ntv, ov, mvars, loss_sum, batches):
                def body(carry, batch):
                    return train_step(*carry, batch), ()

                carry, _ = lax.scan(body, (tv, ntv, ov, mvars, loss_sum),
                                    batches)
                return carry

            jit_chain = jax.jit(train_chain, donate_argnums=(0, 1, 2, 3, 4))

        jit_epoch = None
        cache_steps = 0
        if cache is not None:
            # device-resident epoch: the shared scan program built by
            # DeviceEpochCache (one source for the permutation/slice logic
            # across estimators; see the flax twin)
            from raydp_tpu.parallel.mesh import batch_sharding

            epoch_fn, cache_steps = cache.make_epoch_fn(
                lambda carry, batch: train_step(*carry, batch),
                self.batch_size, self.shuffle,
                batch_sharding=batch_sharding(mesh))
            jit_epoch = jax.jit(epoch_fn, donate_argnums=(0,))

        jit_eval_epoch = None
        eval_tail = None
        eval_cache_rows = 0
        if eval_cache is not None:
            # whole eval pass as one scan dispatch, built by the shared
            # make_epoch_fn; ragged tail as one jitted call where the
            # caller-decided eval_tail_ok rule allows (the flax twin's
            # shape). Carry rides tv/ntv through unchanged — not donated
            from raydp_tpu.parallel.mesh import batch_sharding

            def _eval_scan_step(carry, batch):
                tv, ntv, mvars, loss_sum = carry
                mvars, loss_sum = eval_step(tv, ntv, mvars, loss_sum, batch)
                return tv, ntv, mvars, loss_sum

            eval_epoch_fn, esteps = eval_cache.make_epoch_fn(
                _eval_scan_step, self.batch_size, shuffle=False,
                batch_sharding=batch_sharding(mesh))
            jit_eval_epoch = jax.jit(eval_epoch_fn)
            eval_cache_rows = esteps * self.batch_size
            tail_rows = eval_cache.num_rows - eval_cache_rows
            if tail_rows > 0 and eval_tail_ok:
                eval_tail = {n: a[eval_cache_rows:]
                             for n, a in eval_cache.arrays.items()}
                eval_cache_rows += tail_rows

        def _host_val(a):
            """Host copy of a replicated array (the local replica shard IS
            the full value — collective-free even across processes)."""
            if hasattr(a, "addressable_data"):
                return np.asarray(a.addressable_data(0))
            return np.asarray(a)

        def _sync_model():
            """Write the device state back into the keras variables."""
            for var, val in zip(model.trainable_variables, tv):
                var.assign(_host_val(val))
            for var, val in zip(model.non_trainable_variables, ntv):
                var.assign(_host_val(val))

        chief = jax.process_index() == 0
        epoch = epoch0
        retries = 0
        saved_this_run = False
        while epoch < self.num_epochs:
            try:
                t0 = _time.perf_counter()
                mvars = _mvars(tm_init)
                loss_sum = jnp.zeros((), jnp.float32)
                steps, samples = 0, 0
                t_feed = t_disp = 0.0
                if cache is not None:
                    td = _time.perf_counter()
                    ekey = jax.random.fold_in(
                        jax.random.PRNGKey(self.seed), epoch)
                    tv, ntv, ov, mvars, loss_sum = jit_epoch(
                        (tv, ntv, ov, mvars, loss_sum), cache.arrays, ekey)
                    # fetch the loss scalar INSIDE this window: dispatch is
                    # async, and dispatch_time_s must carry the epoch's
                    # device time (see the flax twin)
                    loss_sum = np.float32(loss_sum)
                    t_disp = _time.perf_counter() - td
                    steps = cache_steps
                    samples = cache_steps * self.batch_size
                else:
                    feed.set_epoch(epoch)
                    it = feed.chained(chain)
                    while True:
                        tf = _time.perf_counter()
                        nxt = next(it, None)
                        t_feed += _time.perf_counter() - tf
                        if nxt is None:
                            break
                        item, k = nxt
                        td = _time.perf_counter()
                        if chain > 1:  # item is a [k, B, ...] stack, at k=1 too
                            tv, ntv, ov, mvars, loss_sum = jit_chain(
                                tv, ntv, ov, mvars, loss_sum, item)
                        else:
                            tv, ntv, ov, mvars, loss_sum = jit_train(
                                tv, ntv, ov, mvars, loss_sum, item)
                        t_disp += _time.perf_counter() - td
                        steps += k
                        samples += self.batch_size * k
                # fetch the loss scalar BEFORE reading the clock: dispatch is
                # async, so only a host fetch makes the epoch wall include
                # the device work (stable across runs; see flax_estimator)
                ts = _time.perf_counter()
                loss_host = float(loss_sum) / steps if steps else float("nan")
                t_sync = _time.perf_counter() - ts
                dt = _time.perf_counter() - t0
                # registry twin of the epoch report (see the flax estimator)
                from raydp_tpu import metrics as rdt_metrics
                rdt_metrics.observe("train_epoch_seconds", dt)
                # the feed's thread-side decode/stage/h2d split — these walls
                # OVERLAP dispatch (the prefetch win), see the flax twin
                pipe = feed.timings.take() if feed is not None else {}
                report = {
                    "epoch": epoch,
                    "loss": loss_host,
                    "epoch_time_s": dt,
                    "samples_per_s": samples / dt if dt > 0 else 0.0,
                    "feed_time_s": t_feed,
                    "decode_time_s": pipe.get("decode", 0.0),
                    "stage_time_s": pipe.get("stage", 0.0),
                    "h2d_time_s": pipe.get("h2d", 0.0),
                    "dispatch_time_s": t_disp,
                    "sync_time_s": t_sync,
                }
                for m, mv in zip(train_metrics, mvars):
                    report[m.name] = float(m.stateless_result(list(mv)))

                if eval_feed is not None or eval_cache is not None:
                    emv = _mvars(em_init)
                    esum = jnp.zeros((), jnp.float32)
                    if eval_cache is not None:
                        ecnt = eval_cache_rows
                        _, _, emv, esum = jit_eval_epoch(
                            (tv, ntv, emv, esum), eval_cache.arrays,
                            jax.random.PRNGKey(0))  # unused: shuffle=False
                        if eval_tail is not None:
                            emv, esum = jit_eval(tv, ntv, emv, esum,
                                                 eval_tail)
                    else:
                        ecnt = 0
                        for batch in eval_feed:
                            ecnt += int(next(iter(batch.values())).shape[0])
                            emv, esum = jit_eval(tv, ntv, emv, esum, batch)
                    report["val_loss"] = (float(esum) / ecnt) if ecnt \
                        else float("nan")
                    for m, mv in zip(eval_metrics, emv):
                        report[f"val_{m.name}"] = float(
                            m.stateless_result(list(mv)))

                history.append(report)
                logger.info("keras epoch %d: %s", epoch,
                            {k: (round(v, 5) if isinstance(v, float) else v)
                             for k, v in report.items()})
                save_now = save_epoch_now(epoch, self.checkpoint_interval,
                                          self.num_epochs)
                if chief and save_now:
                    # chief-only checkpoint (parity: tf/estimator.py:202-210)
                    # + optimizer sidecar so a resume keeps Adam slots.
                    # Every file lands via tmp+rename and the meta sidecar is
                    # written LAST: a crash mid-save leaves the previous
                    # complete trio, never a torn archive resume trusts
                    _sync_model()
                    tmp_model = saved_model + ".tmp.keras"
                    model.save(tmp_model)
                    os.replace(tmp_model, saved_model)
                    tmp_opt = saved_opt + ".tmp.npz"
                    np.savez(tmp_opt, **{
                        f"v{i}": _host_val(v) for i, v in enumerate(ov)})
                    os.replace(tmp_opt, saved_opt)
                    tmp_meta = saved_meta + ".tmp"
                    with open(tmp_meta, "w") as f:
                        _json.dump({"epoch": epoch, "history": history}, f)
                    os.replace(tmp_meta, saved_meta)
                if save_now:
                    saved_this_run = True
                epoch += 1
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # noqa: BLE001 - FailureConfig parity
                retries += 1
                if retries > max_retries:
                    raise
                logger.warning("keras epoch %d failed (%s); restoring from "
                               "checkpoint (retry %d/%d)", epoch, e, retries,
                               max_retries)
                # adopt a checkpoint only if THIS run (or an explicit resume)
                # wrote/claimed it — a stale dir from an earlier run must not
                # short-circuit a fresh fit to zero epochs
                use_ckpt = (restored or saved_this_run) and _ckpt_available()
                optimizer = keras.saving.deserialize_keras_object(
                    self._optimizer_spec)
                if use_ckpt:
                    model = keras.saving.load_model(saved_model)
                    with open(saved_meta) as f:
                        meta = _json.load(f)
                    epoch = int(meta["epoch"]) + 1
                    history = list(meta["history"])
                    optimizer.build(model.trainable_variables)
                    ov = _restore_opt()
                else:
                    keras.utils.set_random_seed(self.seed)
                    model = self._build_model()
                    model.build(first["features"][:1].shape)
                    epoch = 0
                    history = []
                    optimizer.build(model.trainable_variables)
                    ov = _place([v.value for v in optimizer.variables])
                tv = _place([v.value for v in model.trainable_variables])
                ntv = _place([v.value
                              for v in model.non_trainable_variables])

        _sync_model()
        return model, history

    def _fit_keras_loop(self, train_ds, evaluate_ds=None, max_retries: int = 0
                        ) -> TrainingResult:
        import jax
        keras = _import_keras()

        previous_distribution = self._maybe_distribute()
        try:
            keras.utils.set_random_seed(self.seed)
            model = self._build_model()
            optimizer = keras.saving.deserialize_keras_object(
                self._optimizer_spec)
            model.compile(optimizer=optimizer, loss=self._loss,
                          metrics=list(self._metrics))

            n_dev = len(jax.devices()) if self.data_parallel else 1
            x, y = self._trim(self._materialize(train_ds), n_dev)
            validation = self._materialize(evaluate_ds)
            if validation is not None and n_dev > 1:
                # validation batches must also split evenly over devices
                vx, vy = validation
                n = (len(vx) // n_dev) * n_dev
                validation = (vx[:n], vy[:n]) if n else None

            ckpt_dir = self.checkpoint_dir or tempfile.mkdtemp(
                prefix="rdt-keras-ckpt-")
            os.makedirs(ckpt_dir, exist_ok=True)
            saved_marker = {"saved": False}  # only THIS run's checkpoint may
            # be adopted by a retry — never a stale file from a reused dir
            callbacks = []
            if jax.process_index() == 0:
                # chief-only checkpoint (parity: tf/estimator.py:202-210);
                # the checkpoint_interval knob applies here too (keras's
                # ModelCheckpoint has no epoch-interval arg)
                interval = self.checkpoint_interval
                save_path = os.path.join(ckpt_dir, "model.keras")
                num_epochs = self.num_epochs

                class _IntervalCheckpoint(keras.callbacks.Callback):
                    def on_epoch_end(self, epoch, logs=None):
                        if save_epoch_now(epoch, interval, num_epochs):
                            self.model.save(save_path)
                            saved_marker["saved"] = True

                callbacks.append(_IntervalCheckpoint())

            # per-epoch wall times (keras's History has none), so throughput
            # can be reported steady-state like the FlaxEstimator's
            import time as _time

            epoch_times: list = []

            class _EpochTimer(keras.callbacks.Callback):
                """Times the TRAIN portion of each epoch (clock stops when
                validation starts), matching FlaxEstimator's train-only
                ``samples_per_s`` so bench comparisons are like-for-like."""

                def on_train_begin(self, logs=None):
                    epoch_times.clear()  # retries restart the clock

                def on_epoch_begin(self, epoch, logs=None):
                    self._t0 = _time.perf_counter()
                    self._train_end = None

                def on_test_begin(self, logs=None):
                    if getattr(self, "_t0", None) is not None \
                            and self._train_end is None:
                        self._train_end = _time.perf_counter()

                def on_epoch_end(self, epoch, logs=None):
                    end = self._train_end or _time.perf_counter()
                    epoch_times.append(end - self._t0)

            # first in the list: later callbacks' epoch-end work (e.g. the
            # ModelCheckpoint save) must not land inside the timed window
            callbacks.insert(0, _EpochTimer())

            attempt = 0
            while True:
                try:
                    hist = model.fit(
                        x, y, batch_size=self.batch_size,
                        epochs=self.num_epochs,
                        shuffle=self.shuffle,
                        validation_data=validation,
                        callbacks=callbacks,
                        verbose=0,
                        **self.fit_kwargs)
                    break
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:  # noqa: BLE001 - FailureConfig parity
                    attempt += 1
                    if attempt > max_retries:
                        raise
                    saved = os.path.join(ckpt_dir, "model.keras")
                    if (jax.process_count() == 1 and saved_marker["saved"]
                            and os.path.exists(saved)):
                        logger.warning("keras fit failed (%s); retry %d/%d "
                                       "from checkpoint", e, attempt,
                                       max_retries)
                        model = keras.saving.load_model(saved)
                    else:
                        # multi-host (or no checkpoint yet): a chief-only
                        # checkpoint cannot restore every replica consistently,
                        # so rebuild from the spec with the same seed — the
                        # reference's replay-from-scratch semantics
                        logger.warning("keras fit failed (%s); retry %d/%d "
                                       "from scratch", e, attempt, max_retries)
                        keras.utils.set_random_seed(self.seed)
                        model = self._build_model()
                        model.compile(
                            optimizer=keras.saving.deserialize_keras_object(
                                self._optimizer_spec),
                            loss=self._loss, metrics=list(self._metrics))

            n_rows = int(np.asarray(y).shape[0])
            vs = float(self.fit_kwargs.get("validation_split", 0.0) or 0.0)
            if 0.0 < vs < 1.0:
                # keras holds the tail split out of training; throughput must
                # count only trained rows
                n_rows = int(n_rows * (1.0 - vs))
            history = []
            for i in range(len(hist.epoch)):
                row = {"epoch": i,
                       **{k: float(v[i]) for k, v in hist.history.items()}}
                if i < len(epoch_times) and epoch_times[i] > 0:
                    row["epoch_time_s"] = epoch_times[i]
                    row["samples_per_s"] = n_rows / epoch_times[i]
                    from raydp_tpu import metrics as rdt_metrics
                    rdt_metrics.observe("train_epoch_seconds",
                                        epoch_times[i])
                history.append(row)
            self._trained_model = model
            self._result = TrainingResult(state=model, history=history,
                                          checkpoint_dir=ckpt_dir)
            logger.info("keras fit done: %s",
                        history[-1] if history else "{}")
            return self._result
        finally:
            keras.distribution.set_distribution(previous_distribution)

    # --------------------------------------------------------------- fit_gang
    def fit_gang(self, train_ds, evaluate_ds=None, *, num_workers: int = 2,
                 max_retries: int = 0, job_name: Optional[str] = None,
                 run_timeout: float = 3600.0, start_timeout: float = 180.0,
                 worker_env: Optional[Dict[str, str]] = None
                 ) -> TrainingResult:
        """Train as a gang of ``num_workers`` processes under one global
        ``jax.distributed`` mesh — the across-hosts MWMS analogue
        (tf/estimator.py:171-210 runs one ``train_func`` per Ray Train
        worker). Each rank feeds its slice of every global batch through
        :class:`GangShardIterator`; parameters replicate; XLA inserts the
        gradient collectives. The chief saves ``model.keras`` per epoch and a
        failed gang restarts from it (``checkpoint_dir`` must be shared
        storage on multi-machine gangs, as for FlaxEstimator.fit_gang)."""
        import copy
        import uuid as _uuid

        from raydp_tpu.spmd.job import create_spmd_job

        if self.fit_kwargs:
            # the gang runs only the stateless loop; silently dropping
            # model.fit-only options would mis-train without warning
            raise ValueError(
                "fit_gang does not support fit_kwargs "
                f"({sorted(self.fit_kwargs)}); use fit() for stock "
                "model.fit semantics")
        ckpt_dir = self.checkpoint_dir or tempfile.mkdtemp(
            prefix="rdt-keras-gang-")
        if self.checkpoint_dir and (
                os.path.exists(os.path.join(ckpt_dir, "model.keras"))
                or os.path.exists(os.path.join(ckpt_dir, "state.json"))):
            # gang ranks run with resume=True by design, so a fresh fit_gang
            # pointed at a reused dir silently ADOPTS the earlier run's
            # checkpoint — warn before the ranks start (the flax twin's
            # warn_if_reused_dir, for the keras model.keras/state.json format)
            logger.warning(
                "checkpoint_dir %r already holds a model.keras/state.json "
                "from an earlier run; this gang will RESUME from it — use a "
                "fresh checkpoint_dir per run to train from scratch",
                ckpt_dir)
        train_payload = train_ds.portable()
        eval_payload = (evaluate_ds.portable()
                        if evaluate_ds is not None else None)

        est = copy.copy(self)
        est._trained_model = None
        est._result = None
        est.checkpoint_dir = ckpt_dir

        def _rank_fit(ctx):
            return est._gang_rank_fit(ctx, train_payload, eval_payload,
                                      ckpt_dir)

        job = create_spmd_job(
            job_name or f"kerasfit-{_uuid.uuid4().hex[:6]}", num_workers,
            jax_distributed=True, env=worker_env, timeout=start_timeout)
        attempts = 0
        while True:
            try:
                job.start()
                results = job.run(_rank_fit, timeout=run_timeout)
                job.stop()
                break
            except (KeyboardInterrupt, SystemExit):
                job.stop()
                raise
            except Exception as e:  # noqa: BLE001 - gang restart
                job.stop()
                attempts += 1
                if attempts > max_retries:
                    raise
                logger.warning("keras gang fit failed (%s); restarting from "
                               "last checkpoint (retry %d/%d)", e, attempts,
                               max_retries)

        history = results[0]
        keras = _import_keras()
        saved = os.path.join(ckpt_dir, "model.keras")
        model = keras.saving.load_model(saved) if os.path.exists(saved) \
            else None
        self._trained_model = model
        self._result = TrainingResult(state=model, history=history,
                                      checkpoint_dir=ckpt_dir)
        return self._result

    def _gang_rank_fit(self, ctx, train_payload, eval_payload, ckpt_dir: str):
        """Runs inside each SPMD rank: global mesh, rank-sharded host feed,
        the same jitted stateless loop, resume from the chief checkpoint."""
        import jax

        from raydp_tpu.data.dataset import DistributedDataset
        from raydp_tpu.data.feed import (
            DeviceFeed, GangShardIterator, process_local_batch_rows,
        )
        from raydp_tpu.parallel import batch_sharding, make_mesh

        columns = self._columns()
        mesh = make_mesh()  # jax.devices() is global under the gang
        from raydp_tpu.train.checkpoint import ensure_shared_dir
        ensure_shared_dir(ckpt_dir, "rdt_keras_ckpt_probe")

        row_range = process_local_batch_rows(batch_sharding(mesh),
                                             self.batch_size)
        train_ds = DistributedDataset.from_portable(train_payload)
        feed = DeviceFeed(
            train_ds, self.batch_size, columns, mesh=mesh,
            prefetch_to_device=self.prefetch_to_device,
            host_iter=GangShardIterator(
                train_ds, self.batch_size, ctx.world_size, ctx.rank, columns,
                shuffle=self.shuffle, seed=self.seed, row_range=row_range))
        eval_feed = None
        if eval_payload is not None:
            eval_ds = DistributedDataset.from_portable(eval_payload)
            eval_feed = DeviceFeed(
                eval_ds, self.batch_size, columns, mesh=mesh,
                prefetch_to_device=self.prefetch_to_device,
                host_iter=GangShardIterator(
                    eval_ds, self.batch_size, ctx.world_size, ctx.rank,
                    columns, shuffle=False, seed=self.seed,
                    row_range=row_range))
        _, history = self._stateless_train_loop(
            mesh, feed, eval_feed, ckpt_dir, max_retries=0, resume=True)
        return history

    # ----------------------------------------------------------- fit_on_frame
    # ------------------------------------------------------------ partial_fit
    def _partial_fit_epoch(self, ds, epoch: int) -> Dict[str, float]:
        """One online update, keras flavor: the compiled model persists on
        the estimator and ``model.fit(epochs=1)`` advances it over the
        epoch's materialized rows (keras fit is incremental by contract —
        weights are never reinitialized between calls)."""
        import time as _time

        keras = _import_keras()
        model = self._trained_model
        if model is None or not getattr(self, "_online_compiled", False):
            keras.utils.set_random_seed(self.seed)
            model = self._build_model()
            model.compile(optimizer=keras.saving.deserialize_keras_object(
                self._optimizer_spec), loss=self._loss,
                metrics=list(self._metrics))
            self._trained_model = model
            self._online_compiled = True
            self._online_history: List[Dict[str, float]] = []
        t0 = _time.perf_counter()
        x, y = self._materialize(ds)
        hist = model.fit(x, y, batch_size=self.batch_size, epochs=1,
                         shuffle=False, verbose=0)
        dt = _time.perf_counter() - t0
        report = {"epoch": epoch, "epoch_time_s": dt,
                  "steps": int(np.ceil(len(x) / self.batch_size)),
                  "samples_per_s": len(x) / dt if dt > 0 else 0.0}
        for k, v in hist.history.items():
            report[f"train_{k}" if not k.startswith("train_") else k] = \
                float(v[-1])
        if "train_loss" in report:
            report["train_loss"] = float(report["train_loss"])
        self._online_history.append(report)
        self._result = TrainingResult(state=None,
                                      history=self._online_history)
        return report

    def fit_on_frame(self, train_df, evaluate_df=None, *,
                     fs_directory: Optional[str] = None,
                     stop_etl_after_conversion: bool = False,
                     max_retries: int = 0) -> TrainingResult:
        train_ds, eval_ds = self._convert_frames(
            train_df, evaluate_df, fs_directory=fs_directory,
            stop_etl_after_conversion=stop_etl_after_conversion)
        return self.fit(train_ds, eval_ds, max_retries=max_retries)

    # -------------------------------------------------------------- get_model
    def get_model(self):
        """The trained keras model (parity: tf/estimator.py:306-310)."""
        if self._trained_model is None:
            raise RuntimeError("call fit()/fit_on_frame() first")
        return self._trained_model

    # --------------------------------------------------------- export_serving
    def export_serving(self, export_dir: str) -> str:
        """Serving-bundle export, keras flavor: the trained
        trainable/non-trainable variable lists go through
        ``train/checkpoint.py`` (they are what ``stateless_call`` consumes —
        the restored checkpoint is the weight truth; the pickled model
        object only contributes the architecture), plus the feature-column
        spec :meth:`predict` uses."""
        from raydp_tpu.serve.servable import export_bundle

        model = self.get_model()   # raises if fit() has not run
        state = {
            "tv": [np.asarray(v) for v in model.trainable_variables],
            "ntv": [np.asarray(v) for v in model.non_trainable_variables],
        }
        bundle = {
            "model": model,
            "columns": {"features": (self.feature_columns,
                                     self.feature_dtype)},
        }
        return export_bundle(export_dir, "keras", bundle, state)

    # ---------------------------------------------------------------- predict
    def predict(self, ds, batch_size: Optional[int] = None) -> np.ndarray:
        """Predictions over a dataset's feature columns as one host array
        (row order = dataset block order) — the flax twin's convenience for
        the keras path, via the same jitted ``stateless_call`` machinery the
        train loop uses (one dispatch per batch; ``model.predict``'s own
        per-batch Python loop is what made the r2 keras path slow)."""
        import jax
        import jax.numpy as jnp

        from raydp_tpu.data.feed import HostBatchIterator

        model = self.get_model()   # raises if fit has not run

        trainable = [jnp.asarray(v) for v in model.trainable_variables]
        non_trainable = [jnp.asarray(v)
                         for v in model.non_trainable_variables]

        @jax.jit
        def infer(tv, ntv, inputs):
            preds, _ = model.stateless_call(tv, ntv, inputs, training=False)
            if preds.ndim >= 2 and preds.shape[-1] == 1:
                preds = preds.squeeze(-1)
            return preds.astype(jnp.float32)

        cols = {"features": (self.feature_columns, self.feature_dtype)}
        it = HostBatchIterator(ds, batch_size or self.batch_size, cols,
                               shuffle=False, drop_remainder=False)
        out = [np.asarray(infer(trainable, non_trainable,
                                jnp.asarray(batch["features"])))
               for batch in it]
        if not out:
            return np.empty((0,), np.float32)
        return np.concatenate(out, axis=0)
