"""KerasEstimator: the TFEstimator-parity trainer on Keras 3's JAX backend.

Parity map (reference tf/estimator.py):

- the estimator owns a serialized model *spec*, not a live object — the
  reference serializes the model to JSON and optimizer/loss/metrics through
  keras serialize (96-149) so they rebuild inside workers; here
  ``keras.saving.serialize_keras_object`` round-trips them the same way.
- ``train_func`` opens a ``tf.distribute.MultiWorkerMirroredStrategy`` scope →
  compile → ``to_tf`` dataset → ``model.fit`` (171-210); here the strategy
  scope becomes ``keras.distribution.DataParallel`` over the JAX device mesh —
  collectives are XLA collectives over ICI, no TF runtime involved.
- ``merge_feature_columns`` via ray.data ``Concatenator`` (237-260) — the host
  feed stacks feature columns into one matrix the same way.
- chief-only checkpoint (202-210) — process-0 saves ``model.keras`` per epoch.
- same ``fit`` / ``fit_on_spark`` / ``get_model`` surface (212-310) —
  ``fit`` / ``fit_on_frame`` / ``get_model`` below.

Keras must run on the JAX backend; this module asserts it (the reference
equally hard-requires TF inside its workers).
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from raydp_tpu.log import get_logger
from raydp_tpu.train.estimator import EstimatorInterface, FrameEstimatorInterface
from raydp_tpu.train.flax_estimator import TrainingResult

logger = get_logger("train.keras_estimator")

os.environ.setdefault("KERAS_BACKEND", "jax")


def _import_keras():
    import keras

    if keras.backend.backend() != "jax":
        raise RuntimeError(
            "raydp_tpu.KerasEstimator requires the JAX backend; set "
            "KERAS_BACKEND=jax before the first keras import "
            f"(found {keras.backend.backend()!r})")
    return keras


class KerasEstimator(EstimatorInterface, FrameEstimatorInterface):
    """sklearn-style estimator for Keras models, SPMD over the device mesh."""

    def __init__(
        self,
        model=None,
        model_builder: Optional[Callable] = None,
        optimizer="adam",
        loss: Union[str, Callable] = "mse",
        metrics: Optional[Sequence] = None,
        feature_columns: Optional[Sequence[str]] = None,
        label_column: Optional[str] = None,
        batch_size: int = 64,
        num_epochs: int = 10,
        shuffle: bool = True,
        data_parallel: bool = True,
        checkpoint_dir: Optional[str] = None,
        seed: int = 0,
        feature_dtype=np.float32,
        label_dtype=np.float32,
        drop_last: bool = True,
        fit_kwargs: Optional[Dict] = None,
    ):
        keras = _import_keras()
        if model is None and model_builder is None:
            raise ValueError("pass model or model_builder")
        # serialize the spec so fit() rebuilds fresh objects each run
        # (parity: tf/estimator.py:96-149 JSON/keras-serialize round-trip)
        self._model_spec = (keras.saving.serialize_keras_object(model)
                            if model is not None else None)
        self._model_builder = model_builder
        self._optimizer_spec = keras.saving.serialize_keras_object(
            keras.optimizers.get(optimizer))
        self._loss = loss
        self._metrics = list(metrics or [])
        self.feature_columns = list(feature_columns or [])
        self.label_column = label_column
        self.batch_size = batch_size
        self.num_epochs = num_epochs
        self.shuffle = shuffle
        self.data_parallel = data_parallel
        self.checkpoint_dir = checkpoint_dir
        self.seed = seed
        self.feature_dtype = feature_dtype
        self.label_dtype = label_dtype
        self.drop_last = drop_last
        self.fit_kwargs = dict(fit_kwargs or {})
        self._trained_model = None
        self._result: Optional[TrainingResult] = None

    # ------------------------------------------------------------------ build
    def _build_model(self):
        keras = _import_keras()
        if self._model_spec is not None:
            return keras.saving.deserialize_keras_object(self._model_spec)
        return self._model_builder()

    def _maybe_distribute(self):
        """DataParallel over all local devices when >1 (the MWMS-scope
        analogue, tf/estimator.py:173-176). Returns the caller's previous
        distribution so ``fit`` can restore it."""
        keras = _import_keras()
        previous = keras.distribution.distribution()
        import jax
        if self.data_parallel and len(jax.devices()) > 1:
            keras.distribution.set_distribution(
                keras.distribution.DataParallel())
        return previous

    def _materialize(self, ds):
        """Dataset → (features [n, d], labels [n]) host arrays.

        Feature columns merge into one contiguous matrix (parity:
        ``merge_feature_columns`` Concatenator, tf/estimator.py:237-260)."""
        if ds is None:
            return None
        if not self.feature_columns or self.label_column is None:
            raise ValueError("pass feature_columns and label_column")
        table = ds.to_arrow()
        feats = np.stack(
            [table.column(c).to_numpy(zero_copy_only=False)
             .astype(self.feature_dtype, copy=False)
             for c in self.feature_columns], axis=1)
        labels = (table.column(self.label_column)
                  .to_numpy(zero_copy_only=False)
                  .astype(self.label_dtype, copy=False))
        return feats, labels

    def _trim(self, arrays, n_devices: int):
        """Static shapes under data parallelism: drop the ragged tail so every
        batch splits evenly over devices (same reason the DeviceFeed drops
        remainders — a changing batch dim retraces under jit)."""
        feats, labels = arrays
        if not self.drop_last:
            return feats, labels
        step = self.batch_size
        n = (len(feats) // step) * step
        if n == 0:
            n = (len(feats) // n_devices) * n_devices
        return (feats[:n], labels[:n]) if n else (feats, labels)

    # -------------------------------------------------------------------- fit
    def fit(self, train_ds, evaluate_ds=None, max_retries: int = 0
            ) -> TrainingResult:
        import jax
        keras = _import_keras()

        previous_distribution = self._maybe_distribute()
        try:
            keras.utils.set_random_seed(self.seed)
            model = self._build_model()
            optimizer = keras.saving.deserialize_keras_object(
                self._optimizer_spec)
            model.compile(optimizer=optimizer, loss=self._loss,
                          metrics=list(self._metrics))

            n_dev = len(jax.devices()) if self.data_parallel else 1
            x, y = self._trim(self._materialize(train_ds), n_dev)
            validation = self._materialize(evaluate_ds)
            if validation is not None and n_dev > 1:
                # validation batches must also split evenly over devices
                vx, vy = validation
                n = (len(vx) // n_dev) * n_dev
                validation = (vx[:n], vy[:n]) if n else None

            ckpt_dir = self.checkpoint_dir or tempfile.mkdtemp(
                prefix="rdt-keras-ckpt-")
            callbacks = []
            if jax.process_index() == 0:
                # chief-only checkpoint (parity: tf/estimator.py:202-210)
                callbacks.append(keras.callbacks.ModelCheckpoint(
                    os.path.join(ckpt_dir, "model.keras"),
                    save_best_only=False))

            # per-epoch wall times (keras's History has none), so throughput
            # can be reported steady-state like the FlaxEstimator's
            import time as _time

            epoch_times: list = []

            class _EpochTimer(keras.callbacks.Callback):
                """Times the TRAIN portion of each epoch (clock stops when
                validation starts), matching FlaxEstimator's train-only
                ``samples_per_s`` so bench comparisons are like-for-like."""

                def on_train_begin(self, logs=None):
                    epoch_times.clear()  # retries restart the clock

                def on_epoch_begin(self, epoch, logs=None):
                    self._t0 = _time.perf_counter()
                    self._train_end = None

                def on_test_begin(self, logs=None):
                    if getattr(self, "_t0", None) is not None \
                            and self._train_end is None:
                        self._train_end = _time.perf_counter()

                def on_epoch_end(self, epoch, logs=None):
                    end = self._train_end or _time.perf_counter()
                    epoch_times.append(end - self._t0)

            # first in the list: later callbacks' epoch-end work (e.g. the
            # ModelCheckpoint save) must not land inside the timed window
            callbacks.insert(0, _EpochTimer())

            attempt = 0
            while True:
                try:
                    hist = model.fit(
                        x, y, batch_size=self.batch_size,
                        epochs=self.num_epochs,
                        shuffle=self.shuffle,
                        validation_data=validation,
                        callbacks=callbacks,
                        verbose=0,
                        **self.fit_kwargs)
                    break
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:  # noqa: BLE001 - FailureConfig parity
                    attempt += 1
                    if attempt > max_retries:
                        raise
                    saved = os.path.join(ckpt_dir, "model.keras")
                    if jax.process_count() == 1 and os.path.exists(saved):
                        logger.warning("keras fit failed (%s); retry %d/%d "
                                       "from checkpoint", e, attempt,
                                       max_retries)
                        model = keras.saving.load_model(saved)
                    else:
                        # multi-host (or no checkpoint yet): a chief-only
                        # checkpoint cannot restore every replica consistently,
                        # so rebuild from the spec with the same seed — the
                        # reference's replay-from-scratch semantics
                        logger.warning("keras fit failed (%s); retry %d/%d "
                                       "from scratch", e, attempt, max_retries)
                        keras.utils.set_random_seed(self.seed)
                        model = self._build_model()
                        model.compile(
                            optimizer=keras.saving.deserialize_keras_object(
                                self._optimizer_spec),
                            loss=self._loss, metrics=list(self._metrics))

            n_rows = int(np.asarray(y).shape[0])
            vs = float(self.fit_kwargs.get("validation_split", 0.0) or 0.0)
            if 0.0 < vs < 1.0:
                # keras holds the tail split out of training; throughput must
                # count only trained rows
                n_rows = int(n_rows * (1.0 - vs))
            history = []
            for i in range(len(hist.epoch)):
                row = {"epoch": i,
                       **{k: float(v[i]) for k, v in hist.history.items()}}
                if i < len(epoch_times) and epoch_times[i] > 0:
                    row["epoch_time_s"] = epoch_times[i]
                    row["samples_per_s"] = n_rows / epoch_times[i]
                history.append(row)
            self._trained_model = model
            self._result = TrainingResult(state=model, history=history,
                                          checkpoint_dir=ckpt_dir)
            logger.info("keras fit done: %s",
                        history[-1] if history else "{}")
            return self._result
        finally:
            keras.distribution.set_distribution(previous_distribution)

    # ----------------------------------------------------------- fit_on_frame
    def fit_on_frame(self, train_df, evaluate_df=None, *,
                     fs_directory: Optional[str] = None,
                     stop_etl_after_conversion: bool = False,
                     max_retries: int = 0) -> TrainingResult:
        train_ds, eval_ds = self._convert_frames(
            train_df, evaluate_df, fs_directory=fs_directory,
            stop_etl_after_conversion=stop_etl_after_conversion)
        return self.fit(train_ds, eval_ds, max_retries=max_retries)

    # -------------------------------------------------------------- get_model
    def get_model(self):
        """The trained keras model (parity: tf/estimator.py:306-310)."""
        if self._trained_model is None:
            raise RuntimeError("call fit()/fit_on_frame() first")
        return self._trained_model
