"""Training metrics (parity: the torchmetrics wrapper, torch/torch_metrics.py).

The reference wraps torchmetrics objects with per-epoch update/compute/reset
(torch_metrics.py:21-55). Here each metric is a pair of pure functions so the
update runs *inside* the jitted step (no host sync per batch): ``update`` maps a
batch's (predictions, labels) to summable statistics, ``compute`` turns the
accumulated statistics into the final value on the host at epoch end.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence, Union

import jax.numpy as jnp
import numpy as np


class Metric:
    name: str = "metric"

    def init(self) -> Dict[str, float]:
        return {"sum": 0.0, "count": 0.0}

    def update(self, stats, preds, labels):
        raise NotImplementedError

    def compute(self, stats) -> float:
        return float(stats["sum"] / np.maximum(stats["count"], 1e-12))


class MSE(Metric):
    name = "mse"

    def update(self, stats, preds, labels):
        err = jnp.sum((preds - labels) ** 2)
        return {"sum": stats["sum"] + err, "count": stats["count"] + labels.size}


class RMSE(MSE):
    name = "rmse"

    def compute(self, stats) -> float:
        return float(np.sqrt(stats["sum"] / np.maximum(stats["count"], 1e-12)))


class MAE(Metric):
    name = "mae"

    def update(self, stats, preds, labels):
        err = jnp.sum(jnp.abs(preds - labels))
        return {"sum": stats["sum"] + err, "count": stats["count"] + labels.size}


class Accuracy(Metric):
    name = "accuracy"

    def update(self, stats, preds, labels):
        if preds.ndim > labels.ndim:
            pred_cls = jnp.argmax(preds, axis=-1)
        else:
            pred_cls = (preds > 0.5).astype(jnp.int32)
        hits = jnp.sum((pred_cls == labels.astype(pred_cls.dtype)).astype(jnp.float32))
        return {"sum": stats["sum"] + hits, "count": stats["count"] + labels.shape[0]}


class BinaryCrossEntropy(Metric):
    name = "bce"

    def update(self, stats, preds, labels):
        p = jnp.clip(preds, 1e-7, 1 - 1e-7)
        ll = -jnp.sum(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p))
        return {"sum": stats["sum"] + ll, "count": stats["count"] + labels.size}


_REGISTRY = {m.name: m for m in (MSE(), RMSE(), MAE(), Accuracy(),
                                 BinaryCrossEntropy())}
_REGISTRY["mean_squared_error"] = _REGISTRY["mse"]
_REGISTRY["mean_absolute_error"] = _REGISTRY["mae"]


def build_metrics(specs: Sequence[Union[str, Metric]]) -> List[Metric]:
    """Accept names or instances (parity: torch_metrics.py name-or-instance)."""
    out: List[Metric] = []
    for s in specs or []:
        if isinstance(s, Metric):
            out.append(s)
        elif isinstance(s, str):
            if s not in _REGISTRY:
                raise ValueError(f"unknown metric {s!r}; have {sorted(_REGISTRY)}")
            out.append(_REGISTRY[s])
        else:
            raise TypeError(f"metric spec must be str or Metric, got {type(s)}")
    return out
