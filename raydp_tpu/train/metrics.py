"""Training metrics (parity: the torchmetrics wrapper, torch/torch_metrics.py).

The reference wraps torchmetrics objects with per-epoch update/compute/reset
(torch_metrics.py:21-55). Here each metric is a pair of pure functions so the
update runs *inside* the jitted step (no host sync per batch): ``update`` maps a
batch's (predictions, labels) to summable statistics, ``compute`` turns the
accumulated statistics into the final value on the host at epoch end.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence, Union

import jax.numpy as jnp
import numpy as np


def _row_weights(labels, mask):
    """Per-ELEMENT weights from a per-row 0/1 mask (pad-and-mask feeds):
    broadcast the mask over the label's trailing dims so a padded row's
    elements weigh 0 in both the statistic sum and the count. ``mask=None``
    weighs every element 1 — the pre-mask semantics exactly."""
    if mask is None:
        return jnp.ones_like(labels, dtype=jnp.float32)
    return jnp.broadcast_to(
        mask.reshape((-1,) + (1,) * (labels.ndim - 1)),
        labels.shape).astype(jnp.float32)


class Metric:
    name: str = "metric"

    def init(self) -> Dict[str, float]:
        return {"sum": 0.0, "count": 0.0}

    def update(self, stats, preds, labels, mask=None):
        raise NotImplementedError

    def compute(self, stats) -> float:
        return float(stats["sum"] / np.maximum(stats["count"], 1e-12))


class MSE(Metric):
    name = "mse"

    def update(self, stats, preds, labels, mask=None):
        w = _row_weights(labels, mask)
        err = jnp.sum(((preds - labels) ** 2) * w)
        return {"sum": stats["sum"] + err,
                "count": stats["count"] + jnp.sum(w)}


class RMSE(MSE):
    name = "rmse"

    def compute(self, stats) -> float:
        return float(np.sqrt(stats["sum"] / np.maximum(stats["count"], 1e-12)))


class MAE(Metric):
    name = "mae"

    def update(self, stats, preds, labels, mask=None):
        w = _row_weights(labels, mask)
        err = jnp.sum(jnp.abs(preds - labels) * w)
        return {"sum": stats["sum"] + err,
                "count": stats["count"] + jnp.sum(w)}


class Accuracy(Metric):
    name = "accuracy"

    def update(self, stats, preds, labels, mask=None):
        if preds.ndim > labels.ndim:
            pred_cls = jnp.argmax(preds, axis=-1)
        else:
            pred_cls = (preds > 0.5).astype(jnp.int32)
        hits = (pred_cls == labels.astype(pred_cls.dtype)).astype(jnp.float32)
        if mask is not None:
            hits = hits * mask
            rows = jnp.sum(mask)
        else:
            rows = labels.shape[0]
        return {"sum": stats["sum"] + jnp.sum(hits),
                "count": stats["count"] + rows}


class BinaryCrossEntropy(Metric):
    name = "bce"

    def update(self, stats, preds, labels, mask=None):
        w = _row_weights(labels, mask)
        p = jnp.clip(preds, 1e-7, 1 - 1e-7)
        ll = -jnp.sum((labels * jnp.log(p)
                       + (1 - labels) * jnp.log(1 - p)) * w)
        return {"sum": stats["sum"] + ll,
                "count": stats["count"] + jnp.sum(w)}


_REGISTRY = {m.name: m for m in (MSE(), RMSE(), MAE(), Accuracy(),
                                 BinaryCrossEntropy())}
_REGISTRY["mean_squared_error"] = _REGISTRY["mse"]
_REGISTRY["mean_absolute_error"] = _REGISTRY["mae"]


def build_metrics(specs: Sequence[Union[str, Metric]]) -> List[Metric]:
    """Accept names or instances (parity: torch_metrics.py name-or-instance)."""
    out: List[Metric] = []
    for s in specs or []:
        if isinstance(s, Metric):
            out.append(s)
        elif isinstance(s, str):
            if s not in _REGISTRY:
                raise ValueError(f"unknown metric {s!r}; have {sorted(_REGISTRY)}")
            out.append(_REGISTRY[s])
        else:
            raise TypeError(f"metric spec must be str or Metric, got {type(s)}")
    return out
