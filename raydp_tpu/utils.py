"""Shared utilities.

Capability parity with the reference's ``python/raydp/utils.py``: memory-size parsing
(utils.py:125-146), the balanced block→rank sharding kernel ``divide_blocks``
(utils.py:149-222), node-address discovery (utils.py:34-58), and ``random_split``
(utils.py:67-90). Implementations are original; semantics match the reference's tests
(python/raydp/tests/test_spark_utils.py).
"""

from __future__ import annotations

import math
import re
import socket
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_MEMORY_UNITS = {
    "": 1,
    "K": 2**10,
    "M": 2**20,
    "G": 2**30,
    "T": 2**40,
    "P": 2**50,
}


def parse_memory_size(memory_size) -> int:
    """Parse a human-readable memory size ("512m", "1.5 GB", 1024) into bytes.

    Same accepted grammar as the reference (utils.py:125-146): an optional unit
    letter K/M/G/T with an optional trailing B, case-insensitive, optional space.
    """
    if isinstance(memory_size, (int, float)):
        return int(memory_size)
    s = str(memory_size).strip().upper().replace(" ", "")
    m = re.fullmatch(r"([0-9]*\.?[0-9]+)([KMGTP]?)I?B?", s)
    if not m:
        raise ValueError(f"cannot parse memory size: {memory_size!r}")
    number, unit = m.group(1), m.group(2)
    return int(float(number) * _MEMORY_UNITS[unit])


def memory_string(num_bytes: int) -> str:
    for unit in ("T", "G", "M", "K"):
        q = _MEMORY_UNITS[unit]
        if num_bytes >= q and num_bytes % q == 0:
            return f"{num_bytes // q}{unit}B"
    return str(int(num_bytes))


def divide_blocks(
    blocks: Sequence[int],
    world_size: int,
    shuffle: bool = False,
    shuffle_seed: Optional[int] = None,
) -> Dict[int, List[Tuple[int, int]]]:
    """Balanced assignment of data blocks to ``world_size`` ranks.

    This is the data-sharding kernel that guarantees every rank sees exactly
    ``ceil(total_samples / world_size)`` samples — required so a SPMD training step
    (every device participates in every collective) never deadlocks on a short rank.
    Semantics follow the reference (utils.py:149-222): blocks are strided across
    ranks round-robin, short blocks are topped up by (seeded) resampling, and long
    tails are truncated to the per-rank quota. Returns ``{rank: [(block_index,
    num_samples_from_that_block), ...]}``.
    """
    blocks = list(blocks)
    if len(blocks) < world_size:
        raise ValueError(
            f"not enough blocks ({len(blocks)}) to divide over world_size {world_size}"
        )

    num_blocks_per_rank = math.ceil(len(blocks) / world_size)
    num_samples_per_rank = math.ceil(sum(blocks) / world_size)
    total_num_blocks = num_blocks_per_rank * world_size

    global_indexes = list(range(len(blocks)))
    # wrap around so every rank gets the same number of candidate blocks
    if len(global_indexes) != total_num_blocks:
        global_indexes += global_indexes[: total_num_blocks - len(global_indexes)]

    rng = np.random.RandomState(shuffle_seed if shuffle_seed is not None else 0)
    if shuffle:
        rng.shuffle(global_indexes)

    results: Dict[int, List[Tuple[int, int]]] = {}
    for rank in range(world_size):
        candidates = global_indexes[rank:total_num_blocks:world_size]
        selected: List[Tuple[int, int]] = []
        size = 0
        for idx in candidates:
            if size >= num_samples_per_rank:
                break
            take = min(blocks[idx], num_samples_per_rank - size)
            selected.append((idx, take))
            size += take
        # top up from random blocks until the rank hits its quota
        while size < num_samples_per_rank:
            idx = int(rng.choice(global_indexes))
            take = min(blocks[idx], num_samples_per_rank - size)
            selected.append((idx, take))
            size += take
        results[rank] = selected
    return results


def random_split(df, weights: Sequence[float], seed: Optional[int] = None):
    """Split a frame into frames by normalized weights (reference utils.py:67-90)."""
    total = float(sum(weights))
    fractions = [w / total for w in weights]
    return df.random_split(fractions, seed=seed)


def get_node_address() -> str:
    """Best-effort primary IP of this node (reference utils.py:34-58 uses psutil)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def find_free_port(host: str = "127.0.0.1") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]
