"""Test harness.

Parity with the reference's strategy (SURVEY.md §4): real local runtime, simulated
multi-host topology, kill-based fault injection. The JAX analogue of
``ray.cluster_utils.Cluster`` is a virtual 8-device CPU mesh: we force the host
platform before anything imports jax (must happen at conftest import time).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

# The environment may have imported jax at interpreter start (sitecustomize)
# under a hardware platform; backend init is lazy, so force CPU before any
# test touches a device.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def runtime():
    """A bare actor runtime (no ETL session), torn down after the test."""
    from raydp_tpu.runtime import init_runtime, shutdown_runtime

    rt = init_runtime()
    yield rt
    shutdown_runtime()


@pytest.fixture
def runtime_3nodes():
    """Three virtual nodes for placement/fault tests
    (parity: test_spark_cluster.py:90-110 heterogeneous virtual nodes)."""
    from raydp_tpu.runtime import init_runtime, shutdown_runtime

    rt = init_runtime(virtual_nodes=[
        {"CPU": 4.0, "memory": float(2 << 30)},
        {"CPU": 4.0, "memory": float(2 << 30)},
        {"CPU": 4.0, "memory": float(2 << 30), "accel": 1.0},
    ])
    yield rt
    shutdown_runtime()


@pytest.fixture
def session():
    """A 2-executor ETL session (parity: conftest.py spark_on_ray_2_executors)."""
    import raydp_tpu

    s = raydp_tpu.init("pytest", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    yield s
    raydp_tpu.stop()
