"""Adaptive query execution: equivalence vs the static path + rule units.

The contract mirrors the optimizer matrix (tests/test_etl_optimizer.py): for
ANY plan, results under ``RDT_ETL_AQE=1`` must equal ``=0`` row-for-row
(after a canonical sort — partition structure and row order are NOT part of
the result, and AQE deliberately changes both), and the report's
``aqe_broadcast``/``aqe_split``/``aqe_coalesced`` columns must say exactly
which rule fired. A threshold knob of 0 must disable its rule."""

import numpy as np
import pandas as pd
import pytest

from raydp_tpu.etl import functions as F
from raydp_tpu.etl import optimizer as O


@pytest.fixture(scope="module")
def session():
    import raydp_tpu

    s = raydp_tpu.init("pytest_aqe", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    yield s
    raydp_tpu.stop()


@pytest.fixture(scope="module")
def big(session):
    """Wide-ish frame: int key, string key, two payloads."""
    rng = np.random.RandomState(0)
    n = 6000
    pdf = pd.DataFrame({
        "k": rng.randint(0, 40, n),
        "s": [f"tag{i % 23}" for i in range(n)],
        "a": rng.randint(0, 1000, n).astype(np.int64),
        "b": rng.randint(0, 7, n),
    })
    return session.createDataFrame(pdf, num_partitions=4)


def both_paths(monkeypatch, session, make_df, sort_cols):
    """Action under AQE off and on; assert row-identical; return reports."""
    outs, reports = {}, {}
    for env in ("0", "1"):
        monkeypatch.setenv("RDT_ETL_AQE", env)
        session.engine.reset_shuffle_stage_report()
        outs[env] = (make_df().to_pandas().sort_values(sort_cols)
                     .reset_index(drop=True))
        reports[env] = session.engine.shuffle_stage_report()
    monkeypatch.delenv("RDT_ETL_AQE", raising=False)
    pd.testing.assert_frame_equal(outs["0"], outs["1"])
    assert all(r.get("aqe_broadcast", 0) == 0
               and r.get("aqe_split", 0) == 0
               and r.get("aqe_coalesced", 0) == 0
               for r in reports["0"]), reports["0"]
    return outs["1"], reports


def _aqe(reports, col):
    return sum(r.get(col, 0) for r in reports["1"])


def _stages(reports):
    return [r["stage"] for r in reports["1"]]


# ==== rule (a): broadcast-hash join ================================================
def test_broadcast_join_int_keys_both_orders(monkeypatch, session, big):
    dim = session.createDataFrame(
        pd.DataFrame({"k": np.arange(40), "label": np.arange(40) * 3}),
        num_partitions=2)
    # small side on the right: pre-shuffle broadcast, no shuffle stage at all
    out, reports = both_paths(
        monkeypatch, session,
        lambda: big.join(dim, on="k").select("k", "a", "label"),
        ["k", "a"])
    assert _aqe(reports, "aqe_broadcast") >= 1
    assert "join-left" not in _stages(reports)
    assert "join-right" not in _stages(reports)
    assert (out["label"] == out["k"] * 3).all()
    # small side on the left: the left-broadcast gating (inner join) applies
    out, reports = both_paths(
        monkeypatch, session,
        lambda: dim.join(big, on="k").select("k", "a", "label"),
        ["k", "a"])
    assert _aqe(reports, "aqe_broadcast") >= 1
    assert (out["label"] == out["k"] * 3).all()


def test_broadcast_join_string_keys(monkeypatch, session, big):
    dim = session.createDataFrame(
        pd.DataFrame({"s": [f"tag{i}" for i in range(23)],
                      "slab": np.arange(23)}),
        num_partitions=2)
    out, reports = both_paths(
        monkeypatch, session,
        lambda: big.join(dim, on="s").select("s", "a", "slab"),
        ["s", "a"])
    assert _aqe(reports, "aqe_broadcast") >= 1
    assert len(out) == 6000


def test_broadcast_join_left_outer(monkeypatch, session, big):
    # right side broadcasts under "left outer" (streamed-left rows each
    # appear once, so unmatched left rows survive exactly once)
    dim = session.createDataFrame(
        pd.DataFrame({"k": np.arange(20), "label": np.arange(20) * 2}),
        num_partitions=2)
    out, reports = both_paths(
        monkeypatch, session,
        lambda: big.join(dim, on="k", how="left outer")
        .select("k", "a", "label"),
        ["k", "a"])
    assert _aqe(reports, "aqe_broadcast") >= 1
    assert out["label"].isna().any()  # keys 20..39 have no match


def test_full_outer_join_never_broadcasts(monkeypatch, session, big):
    # neither side may broadcast a full outer join: the broadcast side's
    # unmatched rows would be emitted once per probe partition
    dim = session.createDataFrame(
        pd.DataFrame({"k": np.arange(50), "label": np.arange(50)}),
        num_partitions=2)
    out, reports = both_paths(
        monkeypatch, session,
        lambda: big.join(dim, on="k", how="full outer")
        .select("k", "a", "label"),
        ["k", "a", "label"])
    assert _aqe(reports, "aqe_broadcast") == 0
    assert {"join-left", "join-right"} <= set(_stages(reports))


def test_postmap_broadcast_converts_planned_shuffle_join(monkeypatch,
                                                         session, big):
    """The fallback form: the small (left) side is an aggregation — no
    static estimate exists — so its map stage runs, the measured bytes
    reveal the small side, and the RIGHT side's planned shuffle is dropped
    (no join-right stage) in favor of streaming its partitions."""
    dim = session.createDataFrame(
        pd.DataFrame({"k": np.arange(40), "lab": np.arange(40) * 2}),
        num_partitions=2)
    small_agg = dim.groupBy("k").agg(F.count("lab").alias("c"))
    # keep the big side above the broadcast threshold so only the post-map
    # left conversion can fire
    monkeypatch.setenv("RDT_AQE_BROADCAST_MAX", "20000")
    out, reports = both_paths(
        monkeypatch, session,
        lambda: small_agg.join(big, on="k").select("k", "a", "c"),
        ["k", "a"])
    monkeypatch.delenv("RDT_AQE_BROADCAST_MAX", raising=False)
    assert _aqe(reports, "aqe_broadcast") >= 1
    assert "join-left" in _stages(reports)      # the measured map stage
    assert "join-right" not in _stages(reports)  # the saved shuffle
    assert (out["c"] == 1).all()


def test_broadcast_threshold_zero_disables(monkeypatch, session, big):
    dim = session.createDataFrame(
        pd.DataFrame({"k": np.arange(40), "label": np.arange(40)}),
        num_partitions=2)
    monkeypatch.setenv("RDT_AQE_BROADCAST_MAX", "0")
    out, reports = both_paths(
        monkeypatch, session,
        lambda: big.join(dim, on="k").select("k", "a", "label"),
        ["k", "a"])
    monkeypatch.delenv("RDT_AQE_BROADCAST_MAX", raising=False)
    assert _aqe(reports, "aqe_broadcast") == 0
    assert {"join-left", "join-right"} <= set(_stages(reports))


def test_measured_bytes_overrule_a_lying_estimate(monkeypatch, session, big):
    """A threshold tighter than the small side's ACTUAL bytes: the estimate
    admits the side, the materialized measurement rejects it, and the join
    falls back to the bucketed path — correct either way."""
    dim = session.createDataFrame(
        pd.DataFrame({"k": np.arange(40), "label": np.arange(40)}),
        num_partitions=2)
    monkeypatch.setenv("RDT_AQE_BROADCAST_MAX", "64")  # nothing fits 64 bytes
    out, reports = both_paths(
        monkeypatch, session,
        lambda: big.join(dim, on="k").select("k", "a", "label"),
        ["k", "a"])
    monkeypatch.delenv("RDT_AQE_BROADCAST_MAX", raising=False)
    assert _aqe(reports, "aqe_broadcast") == 0
    assert len(out) == 6000


# ==== rule (b): skew splitting =====================================================
def _skewed_frame(session, rows=24_000, parts=4):
    """~50% hot key, rest unique, unique rows FIRST per chunk so the
    cardinality guard picks row-wise partials and the skew reaches the
    reduce side (grouped partials would collapse the hot key map-side)."""
    rng = np.random.RandomState(5)
    per = rows // parts
    chunks = []
    nxt = 1
    for _ in range(parts):
        nu = per // 2
        ks = np.concatenate([np.arange(nxt, nxt + nu) * 7 + 3,
                             np.zeros(per - nu, dtype=np.int64)])
        nxt += nu
        chunks.append(pd.DataFrame(
            {"k": ks, "v": rng.randint(0, 1000, per).astype(np.int64)}))
    return session.createDataFrame(pd.concat(chunks).reset_index(drop=True),
                                   num_partitions=parts)


def test_skew_split_decomposable_groupagg(monkeypatch, session):
    df = _skewed_frame(session)
    monkeypatch.setenv("RDT_AQE_COALESCE_MIN", "0")  # drop the split floor
    monkeypatch.setenv("RDT_AQE_SKEW_FACTOR", "2")
    out, reports = both_paths(
        monkeypatch, session,
        lambda: df.groupBy("k").agg(F.sum("v").alias("sv"),
                                    F.count("v").alias("n"),
                                    F.mean("v").alias("mv")),
        ["k"])
    monkeypatch.delenv("RDT_AQE_COALESCE_MIN", raising=False)
    monkeypatch.delenv("RDT_AQE_SKEW_FACTOR", raising=False)
    assert _aqe(reports, "aqe_split") >= 1
    # integer sum/count bit-identical; the mean column compared by
    # assert_frame_equal's float equality (same partial tree depth per key
    # is NOT guaranteed, but both_paths already passed — merge order for
    # int inputs is exact in float64 here)
    assert len(out) == 12_000 + 1


def test_skew_split_fallback_aggs_dont_split(monkeypatch, session):
    """Non-decomposable aggs take the single-phase path where a key's rows
    must all reach one task: rule (b) must NOT fire, results identical."""
    df = _skewed_frame(session, rows=8000)
    monkeypatch.setenv("RDT_AQE_COALESCE_MIN", "0")
    monkeypatch.setenv("RDT_AQE_SKEW_FACTOR", "2")
    out, reports = both_paths(
        monkeypatch, session,
        lambda: df.groupBy("k").agg(F.stddev("v").alias("sd")),
        ["k"])
    monkeypatch.delenv("RDT_AQE_COALESCE_MIN", raising=False)
    monkeypatch.delenv("RDT_AQE_SKEW_FACTOR", raising=False)
    assert _aqe(reports, "aqe_split") == 0
    assert _stages(reports) == ["groupagg"]


def test_skew_split_join_probe_side(monkeypatch, session):
    """A skewed probe (left) side splits across join tasks, each probing
    the same right bucket; the concat of splits is the bucket's join."""
    df = _skewed_frame(session, rows=16_000)
    dim_keys = np.concatenate([[0], np.arange(1, 8001) * 7 + 3])
    dim = session.createDataFrame(
        pd.DataFrame({"k": dim_keys, "lab": dim_keys * 5}),
        num_partitions=2)
    monkeypatch.setenv("RDT_AQE_COALESCE_MIN", "0")
    monkeypatch.setenv("RDT_AQE_SKEW_FACTOR", "2")
    # force the bucketed path (no broadcast) so the probe-split is what runs
    monkeypatch.setenv("RDT_AQE_BROADCAST_MAX", "0")
    out, reports = both_paths(
        monkeypatch, session,
        lambda: df.join(dim, on="k").select("k", "v", "lab"),
        ["k", "v"])
    for k in ("RDT_AQE_COALESCE_MIN", "RDT_AQE_SKEW_FACTOR",
              "RDT_AQE_BROADCAST_MAX"):
        monkeypatch.delenv(k, raising=False)
    assert _aqe(reports, "aqe_split") >= 1
    assert (out["lab"] == out["k"] * 5).all()
    assert len(out) == 16_000


def test_skew_split_gated_off_for_right_emitting_joins(monkeypatch, session):
    """A right/full-outer (or right semi/anti) join may NOT split its probe
    side: every split probes the WHOLE right bucket, so a right-side row
    that survives on its own (unmatched outer row, semi/anti hit) would be
    emitted once per split. The gate mirrors BROADCAST_RIGHT_JOIN_TYPES —
    and both_paths' row-identity assertion is the regression: without the
    gate, unmatched right rows appear k times under AQE."""
    df = _skewed_frame(session, rows=16_000)
    # right side has keys the skewed left never produces → unmatched rows
    dim_keys = np.concatenate([[0], np.arange(1, 2001) * 7 + 3,
                               np.arange(1, 101) * 1_000_003 + 11])
    dim = session.createDataFrame(
        pd.DataFrame({"k": dim_keys, "lab": dim_keys * 5}),
        num_partitions=2)
    monkeypatch.setenv("RDT_AQE_COALESCE_MIN", "0")
    monkeypatch.setenv("RDT_AQE_SKEW_FACTOR", "2")
    monkeypatch.setenv("RDT_AQE_BROADCAST_MAX", "0")  # force the bucketed path
    out, reports = both_paths(
        monkeypatch, session,
        lambda: df.join(dim, on="k", how="right outer")
        .select("k", "v", "lab"),
        ["k", "v", "lab"])
    for k in ("RDT_AQE_COALESCE_MIN", "RDT_AQE_SKEW_FACTOR",
              "RDT_AQE_BROADCAST_MAX"):
        monkeypatch.delenv(k, raising=False)
    assert _aqe(reports, "aqe_split") == 0
    # the 100 never-matching right keys survive exactly once each
    assert int(out["v"].isna().sum()) == 100


def test_skew_factor_zero_disables(monkeypatch, session):
    df = _skewed_frame(session, rows=8000)
    monkeypatch.setenv("RDT_AQE_COALESCE_MIN", "0")
    monkeypatch.setenv("RDT_AQE_SKEW_FACTOR", "0")
    _, reports = both_paths(
        monkeypatch, session,
        lambda: df.groupBy("k").agg(F.sum("v").alias("sv")),
        ["k"])
    monkeypatch.delenv("RDT_AQE_COALESCE_MIN", raising=False)
    monkeypatch.delenv("RDT_AQE_SKEW_FACTOR", raising=False)
    assert _aqe(reports, "aqe_split") == 0


# ==== rule (c): tiny-partition coalescing ==========================================
def test_repartition_coalescing(monkeypatch, session, big):
    out, reports = both_paths(
        monkeypatch, session,
        lambda: big.repartition(8).select("k", "a"),
        ["k", "a"])
    assert _aqe(reports, "aqe_coalesced") >= 1
    assert len(out) == 6000


def test_groupagg_and_distinct_coalescing(monkeypatch, session, big):
    out, reports = both_paths(
        monkeypatch, session,
        lambda: big.groupBy("k", "b").agg(F.sum("a").alias("sa")),
        ["k", "b"])
    assert _aqe(reports, "aqe_coalesced") >= 1
    out, reports = both_paths(
        monkeypatch, session,
        lambda: big.select("k", "b").distinct(),
        ["k", "b"])
    assert _aqe(reports, "aqe_coalesced") >= 1


def test_coalesce_min_zero_disables(monkeypatch, session, big):
    monkeypatch.setenv("RDT_AQE_COALESCE_MIN", "0")
    _, reports = both_paths(
        monkeypatch, session,
        lambda: big.repartition(8).select("k", "a"),
        ["k", "a"])
    monkeypatch.delenv("RDT_AQE_COALESCE_MIN", raising=False)
    assert _aqe(reports, "aqe_coalesced") == 0


def test_consolidate_off_disables_index_rules(monkeypatch, session, big):
    """Legacy per-bucket blobs carry no size index: rules (b)/(c) must not
    fire, results identical (the kill switch is read per action)."""
    monkeypatch.setenv("RDT_SHUFFLE_CONSOLIDATE", "0")
    out, reports = both_paths(
        monkeypatch, session,
        lambda: big.repartition(8).select("k", "a"),
        ["k", "a"])
    monkeypatch.delenv("RDT_SHUFFLE_CONSOLIDATE", raising=False)
    assert _aqe(reports, "aqe_coalesced") == 0
    assert _aqe(reports, "aqe_split") == 0
    assert len(out) == 6000


# ==== master switch + edge cases ===================================================
def test_master_switch_off_disables_everything(monkeypatch, session, big):
    dim = session.createDataFrame(
        pd.DataFrame({"k": np.arange(40), "label": np.arange(40)}),
        num_partitions=2)
    monkeypatch.setenv("RDT_ETL_AQE", "0")
    session.engine.reset_shuffle_stage_report()
    big.join(dim, on="k").select("k", "label").to_pandas()
    big.repartition(8).to_pandas()
    reports = session.engine.shuffle_stage_report()
    monkeypatch.delenv("RDT_ETL_AQE", raising=False)
    assert all(r["aqe_broadcast"] == 0 and r["aqe_split"] == 0
               and r["aqe_coalesced"] == 0 for r in reports), reports


def test_empty_frame_edges(monkeypatch, session, big):
    empty = session.createDataFrame(
        pd.DataFrame({"k": np.array([], dtype=np.int64),
                      "label": np.array([], dtype=np.int64)}),
        num_partitions=1)
    out, reports = both_paths(
        monkeypatch, session,
        lambda: big.join(empty, on="k").select("k", "a", "label"),
        ["k", "a"])
    assert len(out) == 0
    out, _ = both_paths(
        monkeypatch, session,
        lambda: empty.groupBy("k").agg(F.count("label").alias("n")),
        ["k"])
    assert len(out) == 0


def test_one_bucket_edge(monkeypatch, session):
    """A single reduce bucket can neither coalesce nor be 'skewed' (no
    median to compare against) — the rules must be clean no-ops."""
    rng = np.random.RandomState(1)
    pdf = pd.DataFrame({"k": rng.randint(0, 5, 500),
                        "v": rng.randint(0, 10, 500)})
    df = session.createDataFrame(pdf, num_partitions=1)
    out, reports = both_paths(
        monkeypatch, session,
        lambda: df.repartition(1).select("k", "v"),
        ["k", "v"])
    assert len(out) == 500
    assert _aqe(reports, "aqe_split") == 0
    assert _aqe(reports, "aqe_coalesced") == 0


def test_estimate_plan_bytes_units():
    from raydp_tpu.etl import plan as P
    from raydp_tpu.runtime.object_store import ObjectRef

    mem = P.InMemory([ObjectRef(id="a" * 32, size=100),
                      ObjectRef(id="b" * 32, size=200)], schema=None)
    assert O.estimate_plan_bytes(mem) == 300
    # row-preserving wrappers pass through; aggregations are unknowable
    assert O.estimate_plan_bytes(P.Limit(mem, 5)) == 300
    assert O.estimate_plan_bytes(
        P.GroupAgg(mem, ["k"], [("v", "sum", "s")])) is None
    rs = P.RangeScan(0, 1000, num_partitions=2)
    est = O.estimate_plan_bytes(rs)
    assert est is not None and est >= 8000
