"""Attach/client mode: a standalone head shared by sequential drivers.

Parity: the reference's test matrix runs in Ray-client mode against a head
that outlives drivers (conftest.py:77-140; cluster-mode driver
test_spark_cluster.py:113-134), and ownership-transferred data survives
``stop_spark(cleanup_data=False)`` (test_from_spark.py:33-69). Here driver 1
attaches to a standalone head process, converts a frame to a dataset owned by
its master, detaches with ``cleanup_data=False``, and driver 2 — a separate
process — attaches later and reads the same dataset out of the head's store.
"""

import os
import pickle
import signal
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _start_head(extra_env=None):
    env = _env()
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "raydp_tpu.runtime.head", "--listen",
         "--port", "0"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        start_new_session=True, text=True)
    deadline = time.time() + 60.0
    address = None
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("RDT_HEAD_READY "):
            address = line.split()[1].strip()
            break
    if address is None:
        proc.kill()
        raise RuntimeError("standalone head never became ready")
    return proc, address


def _run_driver(body: str, address: str, payload_path: str):
    script = textwrap.dedent(f"""
        import jax
        jax.config.update("jax_platforms", "cpu")
        ADDRESS = {address!r}
        PAYLOAD = {payload_path!r}
    """) + textwrap.dedent(body)
    res = subprocess.run([sys.executable, "-c", script], env=_env(),
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, f"driver failed:\n{res.stdout}\n{res.stderr}"
    return res


def _kill(proc):
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        try:
            proc.kill()
        except ProcessLookupError:
            pass


def test_two_sequential_drivers_share_one_head(tmp_path):
    head, address = _start_head()
    payload_path = str(tmp_path / "payload.pkl")
    try:
        _run_driver("""
            import pickle
            import numpy as np
            import pandas as pd
            import raydp_tpu
            from raydp_tpu.data.dataset import from_frame

            s = raydp_tpu.init("driver1", num_executors=2, executor_cores=1,
                               executor_memory="256MB", address=ADDRESS)
            pdf = pd.DataFrame({"x": np.arange(1000, dtype=np.int64),
                                "y": np.arange(1000) * 2.0})
            df = s.createDataFrame(pdf, num_partitions=4)
            ds = from_frame(df)          # blocks owned by driver1's master
            with open(PAYLOAD, "wb") as f:
                pickle.dump(ds.portable(), f)
            # keep the master (and the data it owns) alive for driver 2
            raydp_tpu.stop(cleanup_data=False)
        """, address, payload_path)

        _run_driver("""
            import pickle
            import numpy as np
            import raydp_tpu
            from raydp_tpu.data.dataset import DistributedDataset

            s = raydp_tpu.init("driver2", num_executors=1, executor_cores=1,
                               executor_memory="256MB", address=ADDRESS)
            with open(PAYLOAD, "rb") as f:
                payload = pickle.load(f)
            ds = DistributedDataset.from_portable(payload)
            assert ds.count() == 1000, ds.count()
            table = ds.to_arrow()
            x = np.sort(table.column("x").to_numpy())
            assert (x == np.arange(1000)).all()
            # driver1's master must still be resolvable by name
            from raydp_tpu.runtime import get_runtime
            assert get_runtime().get_actor("driver1_MASTER") is not None
            raydp_tpu.stop()
        """, address, payload_path)
    finally:
        _kill(head)


def test_driver_crash_leaves_head_usable_and_reaps_actors(tmp_path):
    """A driver that exits without detaching must not poison the head: the
    next driver attaches and works, and the crasher's still-bound actors are
    reaped once its heartbeats stop (Ray's non-detached-actor lifetime) —
    a long-lived head must not accumulate leaked sessions."""
    head, address = _start_head({"RDT_DRIVER_REAP_S": "8"})
    payload_path = str(tmp_path / "unused.pkl")
    try:
        script = textwrap.dedent(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import os
            import raydp_tpu
            s = raydp_tpu.init("crasher", num_executors=1, executor_cores=1,
                               executor_memory="256MB", address={address!r})
            s.range(100).count()
            os._exit(1)  # die without stop()
        """)
        subprocess.run([sys.executable, "-c", script], env=_env(),
                       capture_output=True, timeout=300)

        _run_driver("""
            import time
            import raydp_tpu
            s = raydp_tpu.init("survivor", num_executors=1, executor_cores=1,
                               executor_memory="256MB", address=ADDRESS)
            assert s.range(500).count() == 500
            # the crasher's session actors disappear after its heartbeats
            # lapse (head runs with RDT_DRIVER_REAP_S=8)
            from raydp_tpu.runtime import get_runtime
            rt = get_runtime()
            deadline = time.time() + 60.0
            while time.time() < deadline:
                if rt.get_actor("crasher_MASTER") is None:
                    break
                time.sleep(1.0)
            assert rt.get_actor("crasher_MASTER") is None, \\
                "crashed driver's master leaked"
            raydp_tpu.stop()
        """, address, payload_path)
    finally:
        _kill(head)


def test_driver_inside_runtime_actor(tmp_path):
    """Cluster mode: a FULL driver session (init → ETL → fit → stop) running
    INSIDE a runtime actor, not in the attaching process (VERDICT r3 missing
    #2; parity: the reference runs a Spark driver inside a Ray actor,
    reference test_spark_cluster.py:113-134)."""
    head, address = _start_head()
    payload_path = str(tmp_path / "inner.pkl")
    try:
        _run_driver("""
            import raydp_tpu
            from raydp_tpu.runtime import get_runtime

            class InnerDriver:
                def run(self, address):
                    # the actor process becomes a driver of the same head
                    import jax
                    jax.config.update("jax_platforms", "cpu")
                    import numpy as np
                    import pandas as pd
                    import optax
                    import raydp_tpu
                    from raydp_tpu.data import from_frame
                    from raydp_tpu.models import MLP
                    from raydp_tpu.train import FlaxEstimator

                    s = raydp_tpu.init(
                        "inner-app", num_executors=2, executor_cores=1,
                        executor_memory="256MB", address=address)
                    rng = np.random.RandomState(0)
                    pdf = pd.DataFrame({"x": rng.rand(2000),
                                        "z": rng.rand(2000),
                                        "y": rng.rand(2000)})
                    df = s.createDataFrame(pdf, num_partitions=4)
                    n = df.count()
                    est = FlaxEstimator(
                        model=MLP(features=(8,), use_batch_norm=False),
                        optimizer=optax.adam(1e-2), loss="mse",
                        feature_columns=["x", "z"], label_column="y",
                        batch_size=128, num_epochs=2, seed=0)
                    result = est.fit(from_frame(df))
                    raydp_tpu.stop()
                    return {"rows": n,
                            "epochs": len(result.history),
                            "loss": result.history[-1]["train_loss"]}

            s = raydp_tpu.init("outer", num_executors=1, executor_cores=1,
                               executor_memory="256MB", address=ADDRESS)
            rt = get_runtime()
            actor = rt.create_actor(InnerDriver, name="inner-driver",
                                    resources={"CPU": 1.0})
            out = actor.call("run", ADDRESS, timeout=240.0)
            assert out["rows"] == 2000
            assert out["epochs"] == 2
            assert out["loss"] == out["loss"]  # finite
            raydp_tpu.stop()
        """, address, payload_path)
    finally:
        _kill(head)
