"""Client-mode matrix: the full ETL→train stack through an attached driver.

Parity: the reference parametrizes its suite over direct vs Ray-client mode
(reference conftest.py:77-140) — every Spark/estimator feature must work when
the driver is a client of a remote head. This runs a representative slice of
the stack (reads, expressions, groupBy/join/sort shuffles, dataset
conversion, estimator training, dynamic allocation) inside one attached
driver process against a standalone head.
"""

import os
import subprocess
import sys
import textwrap

from tests.test_attach import _env, _kill, _start_head


def test_full_stack_through_attached_driver():
    head, address = _start_head()
    try:
        script = textwrap.dedent(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import pandas as pd
            import optax
            import raydp_tpu
            from raydp_tpu.data import from_frame
            from raydp_tpu.etl import functions as F
            from raydp_tpu.etl.expressions import col
            from raydp_tpu.models import MLP
            from raydp_tpu.train import FlaxEstimator
            from raydp_tpu.utils import random_split

            s = raydp_tpu.init("matrix", num_executors=2, executor_cores=1,
                               executor_memory="512MB",
                               address={address!r})

            # narrow + wide operators over the client session
            rng = np.random.RandomState(0)
            n = 4000
            pdf = pd.DataFrame({{
                "k": rng.randint(0, 7, n),
                "x": rng.rand(n),
                "y": rng.rand(n) * 2.0,
            }})
            df = s.createDataFrame(pdf, num_partitions=4)
            assert df.count() == n
            filtered = df.filter(col("x") > 0.5)
            assert 0 < filtered.count() < n

            agg = (df.groupBy("k").agg(F.mean("x").alias("mx"))
                   .to_pandas().set_index("k"))
            exp = pdf.groupby("k")["x"].mean()
            for k in exp.index:
                assert abs(agg.loc[k, "mx"] - exp[k]) < 1e-9

            srt = df.sort("k", "x").to_pandas().reset_index(drop=True)
            exp_s = pdf.sort_values(["k", "x"]).reset_index(drop=True)
            pd.testing.assert_frame_equal(srt, exp_s)

            right = s.createDataFrame(
                pd.DataFrame({{"k": np.arange(7), "name": list("abcdefg")}}),
                num_partitions=2)
            joined = df.join(right, on="k").count()
            assert joined == n

            # dynamic allocation over the client RPC
            assert s.request_total_executors(3) == 3
            assert s.request_total_executors(2) == 2

            # conversion + estimator training on the attached session
            train_df, test_df = random_split(df, [0.8, 0.2], seed=0)
            est = FlaxEstimator(
                model=MLP(features=(8,), use_batch_norm=False),
                optimizer=optax.adam(1e-2), loss="mse",
                feature_columns=["x", "k"], label_column="y",
                batch_size=128, num_epochs=2, seed=0)
            result = est.fit(from_frame(train_df), from_frame(test_df))
            assert len(result.history) == 2
            assert "eval_loss" in result.history[-1]

            raydp_tpu.stop()
        """)
        res = subprocess.run([sys.executable, "-c", script], env=_env(),
                             capture_output=True, text=True, timeout=600)
        assert res.returncode == 0, \
            f"client-mode stack failed:\n{res.stdout[-2000:]}\n" \
            f"{res.stderr[-4000:]}"
    finally:
        _kill(head)


def test_placement_group_through_attached_driver():
    """Attach-mode pg pre-allocation (VERDICT r3 missing #1): the group is
    created on the HEAD's resource model over RPC, executors pin to its
    bundles, and stop() removes it — parity with the reference's client-mode
    pg path (reference context.py:119-140, conftest.py:77-140)."""
    head, address = _start_head()
    try:
        script = textwrap.dedent(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import pandas as pd
            import raydp_tpu
            from raydp_tpu.runtime import get_runtime

            s = raydp_tpu.init("pg-client", num_executors=2, executor_cores=1,
                               executor_memory="256MB",
                               placement_group_strategy="SPREAD",
                               address={address!r})
            rt = get_runtime()
            groups = rt.head.call("list_placement_groups")
            assert len(groups) == 1, groups
            assert len(groups[0]["bundles"]) == 2
            assert all(b["node_id"] for b in groups[0]["bundles"])

            # the session actually works on the pg-pinned executors
            df = s.createDataFrame(
                pd.DataFrame({{"x": np.arange(500)}}), num_partitions=4)
            assert df.count() == 500
            raydp_tpu.stop()
        """)
        res = subprocess.run([sys.executable, "-c", script], env=_env(),
                             capture_output=True, text=True, timeout=300)
        assert res.returncode == 0, \
            f"pg client-mode failed:\n{res.stdout[-2000:]}\n" \
            f"{res.stderr[-4000:]}"

        # a fresh driver sees no leftover group: stop() removed it on the head
        check = textwrap.dedent(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import raydp_tpu
            from raydp_tpu.runtime import get_runtime

            s = raydp_tpu.init("pg-check", num_executors=1, executor_cores=1,
                               executor_memory="256MB", address={address!r})
            assert get_runtime().head.call("list_placement_groups") == []
            raydp_tpu.stop()
        """)
        res = subprocess.run([sys.executable, "-c", check], env=_env(),
                             capture_output=True, text=True, timeout=300)
        assert res.returncode == 0, \
            f"pg cleanup check failed:\n{res.stdout[-2000:]}\n" \
            f"{res.stderr[-4000:]}"
    finally:
        _kill(head)
