"""Bench-harness control flow: the budget/cap/wedge machinery that decides
whether a round records numbers at all (r03 recorded nothing; r04's tunnel
wedged mid-matrix). Probe and config children are faked so the logic is
testable without hardware."""

import importlib.util
import json
import os
import sys

import pytest


@pytest.fixture
def bench(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # keep the compile-cache setup away from the repo during tests
    monkeypatch.setenv("RDT_JAX_CACHE_DIR", str(tmp_path / "jc"))
    return mod


def _run_main(bench, capsys):
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()[-1]
    return json.loads(out)


def test_mid_matrix_wedge_falls_back_to_cpu(bench, monkeypatch, capsys):
    """A config timeout on the TPU platform + a failed re-probe must switch
    the REST of the matrix to the labeled CPU fallback (r04: a mid-matrix
    wedge made every later config burn its full cap)."""
    monkeypatch.setenv("BENCH_CONFIGS", "nyctaxi,gbdt,keras")
    calls = []

    def fake_spawn(name, cap_s, platform):
        calls.append((name, platform))
        if name == "nyctaxi":
            return {"samples_per_s_per_chip": 1000.0}
        if name == "gbdt":
            return {"timeout_s": cap_s, "error": "wall cap"}
        return {"samples_per_s_per_chip": 5.0}

    monkeypatch.setattr(bench, "_spawn_config", fake_spawn)
    # startup probe says TPU; the mid-run re-probe (after gbdt's timeout)
    # hangs — exactly the wedge signature
    probes = iter(["tpu", None])
    monkeypatch.setattr(bench, "_probe_devices",
                        lambda timeout_s=None: next(probes))

    out = _run_main(bench, capsys)
    assert calls == [("nyctaxi", "default"), ("gbdt", "default"),
                     ("keras", "cpu(tpu-wedged-midrun-fallback)")]
    # the headline ran on TPU and must stay labeled that way
    assert out["platform"] == "default"
    assert out["platform_midrun_fallback"] == "cpu(tpu-wedged-midrun-fallback)"
    assert out["value"] == 1000.0
    assert out["extra"]["keras"]["platform"] == \
        "cpu(tpu-wedged-midrun-fallback)"


def test_wedged_headline_is_labeled_cpu(bench, monkeypatch, capsys):
    """Ordering-proof labeling: when the wedge fires BEFORE the headline
    config, the top-level platform must report the fallback the headline
    actually ran on — never the startup decision."""
    monkeypatch.setenv("BENCH_CONFIGS", "gbdt,nyctaxi")

    def fake_spawn(name, cap_s, platform):
        if name == "gbdt":
            return {"timeout_s": cap_s, "error": "wall cap"}
        return {"samples_per_s_per_chip": 42.0}

    monkeypatch.setattr(bench, "_spawn_config", fake_spawn)
    probes = iter(["tpu", "cpu"])  # dead tunnel: plugin falls back to host
    monkeypatch.setattr(bench, "_probe_devices",
                        lambda timeout_s=None: next(probes))

    out = _run_main(bench, capsys)
    assert out["platform"] == "cpu(tpu-wedged-midrun-fallback)"
    assert out["value"] == 42.0


def test_budget_skips_are_explicit(bench, monkeypatch, capsys):
    """Configs that do not fit the budget are recorded as skipped markers —
    never silently absent (r03's lesson: the driver must always get JSON)."""
    monkeypatch.setenv("BENCH_CONFIGS", "nyctaxi,gbdt")
    monkeypatch.setattr(bench, "BUDGET_S", 0.0)  # read at import time
    monkeypatch.setattr(bench, "_spawn_config",
                        lambda *a: pytest.fail("nothing should spawn"))
    monkeypatch.setattr(bench, "_probe_devices", lambda timeout_s=None: "tpu")

    out = _run_main(bench, capsys)
    assert out["extra"]["nyctaxi"]["skipped"] == "budget"
    assert out["extra"]["gbdt"]["skipped"] == "budget"
    assert out["value"] == 0.0 and out["vs_baseline"] == 0.0


def test_last_config_timeout_skips_reprobe(bench, monkeypatch, capsys):
    """No re-probe after the last config: nothing is left to save, and the
    probe's wall would only overshoot the budget."""
    monkeypatch.setenv("BENCH_CONFIGS", "nyctaxi")
    monkeypatch.setattr(
        bench, "_spawn_config",
        lambda name, cap_s, platform: {"timeout_s": cap_s, "error": "cap"})
    probe_calls = {"n": 0}

    def probe(timeout_s=None):
        probe_calls["n"] += 1
        return "tpu"

    monkeypatch.setattr(bench, "_probe_devices", probe)
    out = _run_main(bench, capsys)
    assert probe_calls["n"] == 1  # the startup probe only
    assert out["value"] == 0.0
