"""Bench-harness control flow: the budget/cap/wedge machinery that decides
whether a round records numbers at all (r03 recorded nothing; r04's tunnel
wedged mid-matrix). Probe and config children are faked so the logic is
testable without hardware."""

import importlib.util
import json
import os
import sys

import pytest


@pytest.fixture
def bench(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # keep the compile-cache setup and the detail record away from the repo
    monkeypatch.setenv("RDT_JAX_CACHE_DIR", str(tmp_path / "jc"))
    monkeypatch.setenv("RDT_BENCH_DETAIL_PATH",
                       str(tmp_path / "BENCH_DETAIL.json"))
    return mod


def _run_main(bench, capsys):
    """Run main() and return the RICH record (BENCH_DETAIL.json). stdout's
    final line is a compact digest sized for the driver's 2000-char tail;
    the detail file carries the full per-config results — consistency of the
    two is asserted here so every test exercises both."""
    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    compact = json.loads(line)
    assert len(line) <= 1900, f"stdout line too big for the driver: {len(line)}"
    # rdtlint: allow[knob-registry] test reads back the path it set above
    with open(os.environ["RDT_BENCH_DETAIL_PATH"]) as fh:
        detail = json.load(fh)
    for key in ("metric", "unit", "platform", "value", "vs_baseline"):
        assert compact.get(key) == detail.get(key), key
    return detail


def test_mid_matrix_wedge_falls_back_to_cpu(bench, monkeypatch, capsys):
    """A config timeout on the TPU platform + a failed re-probe must switch
    the REST of the matrix to the labeled CPU fallback (r04: a mid-matrix
    wedge made every later config burn its full cap)."""
    monkeypatch.setenv("BENCH_CONFIGS", "nyctaxi,gbdt,keras")
    calls = []

    def fake_spawn(name, cap_s, platform):
        calls.append((name, platform))
        if name == "nyctaxi":
            return {"samples_per_s_per_chip": 1000.0}
        if name == "gbdt":
            return {"timeout_s": cap_s, "error": "wall cap"}
        return {"samples_per_s_per_chip": 5.0}

    monkeypatch.setattr(bench, "_spawn_config", fake_spawn)
    # startup probe says TPU; the mid-run re-probe (after gbdt's timeout)
    # hangs — exactly the wedge signature
    probes = iter(["tpu", None])
    monkeypatch.setattr(bench, "_probe_devices",
                        lambda timeout_s=None: next(probes))

    out = _run_main(bench, capsys)
    assert calls == [("nyctaxi", "default"), ("gbdt", "default"),
                     ("keras", "cpu(tpu-wedged-midrun-fallback)")]
    # the headline ran on TPU and must stay labeled that way
    assert out["platform"] == "default"
    assert out["platform_midrun_fallback"] == "cpu(tpu-wedged-midrun-fallback)"
    assert out["value"] == 1000.0
    assert out["extra"]["keras"]["platform"] == \
        "cpu(tpu-wedged-midrun-fallback)"


def test_wedged_startup_defers_priority_until_probe_passes(bench, monkeypatch,
                                                           capsys):
    """When the startup probe fails on a host that SHOULD have a TPU, the
    TPU-priority configs are deferred: non-priority configs run on the
    labeled CPU fallback with a re-probe between them, and the moment a
    probe passes the deferred configs run on the real device (VERDICT r4 #1:
    three rounds lost their TPU numbers to exactly this wedge)."""
    monkeypatch.setenv("BENCH_CONFIGS", "nyctaxi,gbdt")
    monkeypatch.setenv("BENCH_PROBE_IDLE_S", "0")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    calls = []

    def fake_spawn(name, cap_s, platform):
        calls.append((name, platform))
        return {"samples_per_s_per_chip": 777.0}

    monkeypatch.setattr(bench, "_spawn_config", fake_spawn)
    # startup probe wedged; the re-probe after gbdt's CPU run passes
    probes = iter([None, "tpu"])
    monkeypatch.setattr(bench, "_probe_devices",
                        lambda timeout_s=None: next(probes))

    out = _run_main(bench, capsys)
    assert calls == [("gbdt", "cpu(tpu-unavailable-fallback)"),
                     ("nyctaxi", "default")]
    assert out["platform"] == "default"
    assert out["platform_midrun_promoted"] == "default"
    assert out["value"] == 777.0


def test_wedged_never_heals_priority_falls_back_before_budget(bench,
                                                              monkeypatch,
                                                              capsys):
    """A headline deferred behind a tunnel that never heals must still RUN
    (on the labeled CPU fallback) before the budget expires — a skipped
    primary records 0.0, which is worse than an honest CPU number."""
    monkeypatch.setenv("BENCH_CONFIGS", "nyctaxi")
    monkeypatch.setenv("BENCH_PROBE_IDLE_S", "0")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    # too little budget for the probe-wait loop: surrender immediately
    monkeypatch.setattr(bench, "BUDGET_S", 200.0)
    calls = []

    def fake_spawn(name, cap_s, platform):
        calls.append((name, platform))
        return {"samples_per_s_per_chip": 99.0}

    monkeypatch.setattr(bench, "_spawn_config", fake_spawn)
    monkeypatch.setattr(bench, "_probe_devices", lambda timeout_s=None: None)

    out = _run_main(bench, capsys)
    assert calls == [("nyctaxi", "cpu(tpu-unavailable-fallback)")]
    assert out["platform"] == "cpu(tpu-unavailable-fallback)"
    assert out["value"] == 99.0


def test_wait_loop_keeps_probing_when_nothing_else_to_run(bench, monkeypatch,
                                                          capsys):
    """With only TPU-priority configs pending and budget to spare, the
    scheduler waits on the tunnel (probe, idle, probe ...) instead of
    burning the flagship on a CPU fallback it does not need."""
    monkeypatch.setenv("BENCH_CONFIGS", "nyctaxi")
    monkeypatch.setenv("BENCH_PROBE_IDLE_S", "0")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    probes = iter([None, None, "tpu"])
    probe_calls = {"n": 0}

    def probe(timeout_s=None):
        probe_calls["n"] += 1
        return next(probes)

    monkeypatch.setattr(bench, "_probe_devices", probe)
    monkeypatch.setattr(bench, "_spawn_config",
                        lambda name, cap_s, platform:
                        {"samples_per_s_per_chip": 123.0,
                         "ran_on": platform})

    out = _run_main(bench, capsys)
    assert probe_calls["n"] == 3
    assert out["extra"]["nyctaxi"]["ran_on"] == "default"
    assert out["value"] == 123.0


def test_tpu_timeout_requeues_priority_once(bench, monkeypatch, capsys):
    """A TPU-priority config that blows its cap on a live TPU gets ONE
    requeue (the retry rides the compile cache the killed attempt warmed);
    the failed attempt stays on the record as prior_attempt."""
    monkeypatch.setenv("BENCH_CONFIGS", "nyctaxi,gbdt")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    calls = []

    def fake_spawn(name, cap_s, platform):
        calls.append((name, platform))
        if name == "nyctaxi" and calls.count(("nyctaxi", "default")) == 1:
            return {"timeout_s": cap_s, "error": "wall cap"}
        return {"samples_per_s_per_chip": 555.0}

    monkeypatch.setattr(bench, "_spawn_config", fake_spawn)
    monkeypatch.setattr(bench, "_probe_devices", lambda timeout_s=None: "tpu")

    out = _run_main(bench, capsys)
    assert calls == [("nyctaxi", "default"), ("gbdt", "default"),
                     ("nyctaxi", "default")]
    assert out["value"] == 555.0
    assert "timeout_s" in out["extra"]["nyctaxi"]["prior_attempt"]


def test_budget_skips_are_explicit(bench, monkeypatch, capsys):
    """Configs that do not fit the budget are recorded as skipped markers —
    never silently absent (r03's lesson: the driver must always get JSON)."""
    monkeypatch.setenv("BENCH_CONFIGS", "nyctaxi,gbdt")
    monkeypatch.setattr(bench, "BUDGET_S", 0.0)  # read at import time
    monkeypatch.setattr(bench, "_spawn_config",
                        lambda *a: pytest.fail("nothing should spawn"))
    monkeypatch.setattr(bench, "_probe_devices", lambda timeout_s=None: "tpu")

    out = _run_main(bench, capsys)
    assert out["extra"]["nyctaxi"]["skipped"] == "budget"
    assert out["extra"]["gbdt"]["skipped"] == "budget"
    assert out["value"] == 0.0 and out["vs_baseline"] == 0.0


def test_last_config_timeout_skips_reprobe(bench, monkeypatch, capsys):
    """No re-probe after the last config: nothing is left to save, and the
    probe's wall would only overshoot the budget."""
    monkeypatch.setenv("BENCH_CONFIGS", "nyctaxi")
    monkeypatch.setattr(
        bench, "_spawn_config",
        lambda name, cap_s, platform: {"timeout_s": cap_s, "error": "cap"})
    probe_calls = {"n": 0}

    def probe(timeout_s=None):
        probe_calls["n"] += 1
        return "tpu"

    monkeypatch.setattr(bench, "_probe_devices", probe)
    out = _run_main(bench, capsys)
    assert probe_calls["n"] == 1  # the startup probe only
    assert out["value"] == 0.0


class _FakeProc:
    """Popen stub: first communicate may raise TimeoutExpired; the retry
    returns whatever stdout the child had printed before the kill."""

    pid = 4242
    returncode = 0

    def __init__(self, stdout, timeout_first=False):
        self._stdout = stdout
        self._timeout_first = timeout_first

    def communicate(self, timeout=None):
        import subprocess
        if self._timeout_first:
            self._timeout_first = False
            raise subprocess.TimeoutExpired(cmd="fake", timeout=timeout)
        return self._stdout, ""


def test_spawn_config_last_marker_line_wins(bench, monkeypatch):
    """Configs checkpoint partial matrices as marker lines; the final
    (most complete) line is the result."""
    lines = (bench.RESULT_MARK + json.dumps({"flash": 1}) + "\n"
             + bench.RESULT_MARK + json.dumps({"flash": 1, "dense": 2}) + "\n")
    monkeypatch.setattr(bench.subprocess, "Popen",
                        lambda *a, **k: _FakeProc(lines))
    out = bench._spawn_config("transformer", 60.0, "default")
    assert out == {"flash": 1, "dense": 2}


def test_spawn_config_salvages_partial_on_cap_kill(bench, monkeypatch):
    """A cap kill mid-config keeps the entries measured before the stall
    (code-review r5: a fused2 compile stall must not erase flash/dense)."""
    lines = bench.RESULT_MARK + json.dumps({"flash": {"mfu": 0.59}}) + "\n"
    monkeypatch.setattr(bench.subprocess, "Popen",
                        lambda *a, **k: _FakeProc(lines, timeout_first=True))
    monkeypatch.setattr(bench, "_kill_group", lambda proc: None)
    out = bench._spawn_config("transformer", 60.0, "default")
    assert out["flash"] == {"mfu": 0.59}
    assert out["partial"] is True and out["timeout_s"] == 60.0


def test_partial_tpu_results_survive_fallback_rerun(bench, monkeypatch,
                                                    capsys):
    """When a salvaged-partial TPU attempt is requeued and rerun, the rerun
    keeps the WHOLE partial (real device numbers) as prior_attempt."""
    monkeypatch.setenv("BENCH_CONFIGS", "transformer")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    calls = {"n": 0}

    def fake_spawn(name, cap_s, platform):
        calls["n"] += 1
        if calls["n"] == 1:
            return {"flash": {"mfu": 0.59}, "partial": True,
                    "timeout_s": cap_s, "error": "wall cap"}
        return {"flash": {"mfu": 0.6}, "dense": {"mfu": 0.2}}

    monkeypatch.setattr(bench, "_spawn_config", fake_spawn)
    monkeypatch.setattr(bench, "_probe_devices", lambda timeout_s=None: "tpu")

    out = _run_main(bench, capsys)
    entry = out["extra"]["transformer"]
    assert entry["dense"] == {"mfu": 0.2}
    assert entry["prior_attempt"]["flash"] == {"mfu": 0.59}


def test_spawn_config_crashed_child_after_marker_tagged_partial(bench,
                                                                monkeypatch):
    """A child that dies AFTER printing a checkpoint marker must not read as
    a clean result — incremental checkpoints broke the old any-marker=success
    invariant, so the non-timeout path checks returncode."""
    lines = bench.RESULT_MARK + json.dumps({"flash": {"mfu": 0.59}}) + "\n"
    proc = _FakeProc(lines)
    proc.returncode = 137
    monkeypatch.setattr(bench.subprocess, "Popen", lambda *a, **k: proc)
    out = bench._spawn_config("transformer", 60.0, "default")
    assert out["flash"] == {"mfu": 0.59}
    assert out["partial"] is True and "died rc=137" in out["error"]


def test_stdout_line_fits_driver_tail_and_detail_file_is_full(bench,
                                                              monkeypatch,
                                                              capsys,
                                                              tmp_path):
    """The driver stores only the last 2000 chars of stdout and parses the
    final line out of THAT (r04's rich line was head-truncated and recorded
    as parsed:None). The stdout line must stay compact no matter how big the
    per-config results get; the full record goes to BENCH_DETAIL.json."""
    monkeypatch.setenv("BENCH_CONFIGS", "nyctaxi,transformer,gang")

    big = {"sweep": {str(w): {"samples_per_s": w * 1000.0,
                              "note": "x" * 400} for w in (1, 2, 4)},
           "scaling": {"1": 1.0, "2": 0.6, "4": 0.4},
           "collective_mechanism_ratio": 1.2}

    def fake_spawn(name, cap_s, platform):
        if name == "nyctaxi":
            return {"samples_per_s_per_chip": 1000.0, "pad": "y" * 800}
        if name == "transformer":
            return {"flash": {"tokens_per_s": 83000.0, "mfu": 0.59,
                              "seq_len": 8192, "pad": "z" * 800},
                    "dense": {"tokens_per_s": 1000.0, "seq_len": 4096},
                    "flash_fused2": {"tokens_per_s": 80000.0, "mfu": 0.57,
                                     "seq_len": 8192}}
        return dict(big)

    monkeypatch.setattr(bench, "_spawn_config", fake_spawn)
    monkeypatch.setattr(bench, "_probe_devices", lambda timeout_s=None: "tpu")

    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert len(line) <= 1900, len(line)
    out = json.loads(line)
    assert out["value"] == 1000.0 and out["metric"]
    assert out["extra"]["transformer"]["flash"]["mfu"] == 0.59
    assert out["extra"]["transformer"]["flash_fused2"]["tok_s"] == 80000.0
    assert out["extra"]["gang"]["mechanism_ratio"] == 1.2

    detail = json.loads((tmp_path / "BENCH_DETAIL.json").read_text())
    assert detail["extra"]["nyctaxi"]["pad"] == "y" * 800   # nothing lost
    assert detail["value"] == 1000.0
